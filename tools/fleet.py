#!/usr/bin/env python3
"""Fleet CLI: a supervised, elastically-scaled replica fleet behind one
router address.

    python tools/fleet.py --replicas 2 --min-replicas 1 \
        --max-replicas 4 --router-port 9000 --models llama,simple

Spawns N replica server processes (each a real OS process with its own
port and fault scope), fronts them with a FleetRouter whose membership
the supervisor keeps live, heals replica death (SIGKILL/crash) and
wedges (SIGTERM-drain first) under a bounded restart budget, and
scales the replica count with the fleet's queue pressure
(docs/resilience.md "Fleet supervisor & elastic scaling").

``--manifest DIR`` makes the SUPERVISOR itself crash-durable
(docs/resilience.md "Supervisor crash durability"): fleet state is
journaled to an append-only manifest, and a restarted supervisor
ADOPTS the still-running children instead of respawning a healthy
fleet.  Signal dispositions split with it:

- SIGTERM (manifest mode) = graceful HANDOVER — checkpoint the
  manifest, release the single-writer lock, exit WITHOUT touching the
  children; they keep serving until a successor adopts them.  Pass
  ``--stop-fleet`` to keep SIGTERM as full fleet teardown.
- SIGINT (and SIGTERM without a manifest) = stop the whole fleet,
  drain-first, exactly as before.

The hidden ``--serve-replica`` mode is the replica entry point the
supervisor spawns: one InferenceServer + HttpFrontend on ``--port``
with ``install_sigterm_drain`` installed, exiting once drained.
"""

import argparse
import os
import signal
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "src", "python"))


def build_models(names, slots, spec_tokens=0):
    from tpuserver.models.simple import SimpleModel

    models = []
    if "llama" in names:
        from tpuserver.models import llama
        from tpuserver.models.llama_serving import LlamaGenerateModel

        models.append(LlamaGenerateModel(
            cfg=llama.tiny(vocab=512), max_seq=64, max_slots=slots,
            restart_backoff_s=0.01, spec_tokens=spec_tokens))
    if "simple" in names:
        models.append(SimpleModel())
    if not models:
        raise SystemExit("--models must name llama and/or simple")
    return models


def serve_replica(args):
    """Child mode: one replica server process.  SIGTERM drains first
    (in-flight generations finish, the prober rotates the replica out)
    and the process exits once the server reaches ``stopped``."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from tpuserver.core import InferenceServer, install_sigterm_drain
    from tpuserver.http_frontend import HttpFrontend

    core = InferenceServer(
        build_models(args.models.split(","), args.slots,
                     spec_tokens=args.spec_tokens),
        fault_scope=args.scope or None,
        role=args.role or None,
        spawn_nonce=args.spawn_nonce or None)
    frontend = HttpFrontend(core, port=args.port).start()
    install_sigterm_drain(core, drain_timeout=args.drain_timeout)
    print("replica[{}] serving on {} (pid {})".format(
        args.scope or "-", frontend.url, os.getpid()), flush=True)
    try:
        while core.server_state() != "stopped":
            time.sleep(0.1)
    finally:
        frontend.stop()
    print("replica[{}] drained and stopped".format(args.scope or "-"),
          flush=True)
    return 0


def signal_disposition(signum, manifest, stop_fleet):
    """What one shutdown signal means for THIS supervisor process:
    ``"handover"`` (checkpoint + release the manifest lock + leave the
    children serving) or ``"stop"`` (full drain-first fleet teardown).
    SIGTERM in manifest mode defaults to handover — the whole point of
    the manifest is that restarting the supervisor must not restart
    the fleet — unless ``--stop-fleet`` pins the old teardown
    behaviour; SIGINT (and any signal without a manifest) always
    stops."""
    if (signum == signal.SIGTERM and manifest is not None
            and not stop_fleet):
        return "handover"
    return "stop"


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--serve-replica", action="store_true",
                    help=argparse.SUPPRESS)  # the spawned child mode
    ap.add_argument("--port", type=int, default=0,
                    help="(child mode) replica listen port")
    ap.add_argument("--scope", default="",
                    help="(child mode) fault-injection scope name")
    ap.add_argument("--role", default="",
                    help="(child mode) phase role the replica "
                         "advertises in /v2/health/stats "
                         "(prefill/decode; empty = fused)")
    ap.add_argument("--spawn-nonce", default="",
                    help="(child mode) spawn identity nonce echoed in "
                         "/v2/health/stats — the supervisor's "
                         "adoption contract after its own restart")
    ap.add_argument("--models", default="llama,simple",
                    help="comma list of replica models (llama, simple)")
    ap.add_argument("--slots", type=int, default=4,
                    help="llama scheduler slots per replica (default 4)")
    ap.add_argument("--spec-tokens", type=int, default=0,
                    help="speculative decoding draft budget per replica "
                         "scheduler step (0 = off; token streams are "
                         "identical either way, docs/resilience.md "
                         "'Speculative decoding')")
    ap.add_argument("--drain-timeout", type=float, default=10.0,
                    help="replica SIGTERM drain budget in seconds")
    ap.add_argument("--replicas", type=int, default=2,
                    help="initial replica process count (default 2)")
    ap.add_argument("--prefill-replicas", type=int, default=0,
                    help="disaggregated serving: dedicated prefill "
                         "replicas (requires --decode-replicas too; "
                         "--replicas then only adds fused capacity)")
    ap.add_argument("--decode-replicas", type=int, default=0,
                    help="disaggregated serving: dedicated decode "
                         "replicas the router attaches exported KV "
                         "onto")
    ap.add_argument("--min-replicas", type=int, default=1)
    ap.add_argument("--max-replicas", type=int, default=4)
    ap.add_argument("--router-host", default="127.0.0.1")
    ap.add_argument("--router-port", type=int, default=9000,
                    help="router listen port (0 = pick free)")
    ap.add_argument("--probe-interval", type=float, default=0.5,
                    help="supervisor monitor cadence (default 0.5s)")
    ap.add_argument("--max-restarts", type=int, default=5,
                    help="per-replica restart budget inside the window")
    ap.add_argument("--restart-window", type=float, default=60.0)
    ap.add_argument("--scale-high", type=float, default=0.85,
                    help="sustained fleet utilization that scales UP")
    ap.add_argument("--scale-low", type=float, default=0.10,
                    help="sustained fleet utilization that scales DOWN")
    ap.add_argument("--router-processes", action="store_true",
                    help="supervise the router as its own PROCESS "
                         "(tools/router.py with a crash journal) under "
                         "the same drain-first restart budget the "
                         "replicas get, instead of the in-process "
                         "router")
    ap.add_argument("--router-standby", action="store_true",
                    help="with --router-processes: run a warm-standby "
                         "router tailing the same journal; the "
                         "supervisor promotes it on active-router "
                         "death (clients carrying both urls reconnect "
                         "once, streams resume)")
    ap.add_argument("--router-journal", default=None, metavar="DIR",
                    help="journal directory the router processes "
                         "share (default: a supervisor-owned temp "
                         "directory)")
    ap.add_argument("--standby-port", type=int, default=0,
                    help="standby router listen port (0 = pick free)")
    ap.add_argument("--active-routers", type=int, default=1,
                    help="with --router-processes: N simultaneously-"
                         "active routers partitioning the generation-"
                         "id space (each owns a journal subdirectory "
                         "and peer-forwards siblings' requests); an "
                         "active's death promotes the standby INTO "
                         "its partition (default 1 = single active)")
    ap.add_argument("--manifest", default=None, metavar="DIR",
                    help="supervisor crash durability: journal fleet "
                         "state to this manifest directory; a "
                         "restarted supervisor ADOPTS the running "
                         "children instead of respawning them")
    ap.add_argument("--takeover", action="store_true",
                    help="with --manifest: wait (bounded) for the "
                         "incumbent supervisor's lock instead of "
                         "refusing when one is alive")
    ap.add_argument("--heartbeat-file", default=None, metavar="FILE",
                    help="stamp supervisor liveness + adoption "
                         "counters to this file every monitor tick "
                         "(atomic replace)")
    ap.add_argument("--stop-fleet", action="store_true",
                    help="with --manifest: keep SIGTERM as full fleet "
                         "teardown instead of the default graceful "
                         "handover that leaves children serving")
    ap.add_argument("--stub", action="store_true",
                    help=argparse.SUPPRESS)  # tests/fleet_stub.py
    # replicas: chaos/CI harness mode, no model deps
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    if args.serve_replica:
        return serve_replica(args)

    from tpuserver.fleet import FleetSupervisor

    if args.stub:
        # chaos/CI harness replicas: the pure-stdlib stub server keeps
        # supervisor-kill campaigns fast and model-free
        command = [
            sys.executable, os.path.join(REPO, "tests", "fleet_stub.py"),
            "--port", "{port}", "--scope", "{scope}",
        ]
        if args.spec_tokens > 0:
            command += ["--spec-tokens", str(args.spec_tokens)]
    else:
        command = [
            sys.executable, os.path.abspath(__file__), "--serve-replica",
            "--port", "{port}", "--scope", "{scope}",
            "--models", args.models, "--slots", str(args.slots),
            "--drain-timeout", str(args.drain_timeout),
            "--spec-tokens", str(args.spec_tokens),
        ]
    router_command = None
    if (args.router_processes or args.router_standby
            or args.active_routers > 1):
        router_command = [
            sys.executable, os.path.join(REPO, "tools", "router.py"),
            "--backends", "{backends}", "--host", args.router_host,
            "--port", "{port}", "--journal", "{journal}",
        ]
    supervisor = FleetSupervisor(
        command,
        replicas=args.replicas,
        prefill_replicas=args.prefill_replicas,
        decode_replicas=args.decode_replicas,
        min_replicas=args.min_replicas,
        max_replicas=args.max_replicas,
        probe_interval_s=args.probe_interval,
        max_restarts=args.max_restarts,
        restart_window_s=args.restart_window,
        scale_high=args.scale_high,
        scale_low=args.scale_low,
        router_kwargs={"host": args.router_host, "port": args.router_port},
        router_command=router_command,
        router_standby=args.router_standby,
        router_journal=args.router_journal,
        router_port=args.router_port,
        standby_port=args.standby_port,
        active_routers=args.active_routers,
        env={"PYTHONPATH": os.path.join(REPO, "src", "python")},
        verbose=args.verbose,
        manifest_dir=args.manifest,
        takeover=args.takeover,
        heartbeat_file=args.heartbeat_file,
    ).start()

    stop = threading.Event()
    disposition = {"action": "stop"}

    def _signal(signum, frame):
        disposition["action"] = signal_disposition(
            signum, args.manifest, args.stop_fleet)
        stop.set()

    signal.signal(signal.SIGTERM, _signal)
    signal.signal(signal.SIGINT, _signal)
    print("fleet supervisor: router(s) on {} over {} replica(s) "
          "(min {}, max {}{})".format(
              ", ".join(supervisor.router_urls()), args.replicas,
              args.min_replicas, args.max_replicas,
              ", manifest {}".format(args.manifest)
              if args.manifest else ""), flush=True)
    supervisor.wait_ready(timeout_s=120.0)
    for rep in supervisor.stats()["replicas"]:
        print("  replica {url} [{scope}] pid={pid} state={state}".format(
            **rep), flush=True)
    try:
        stop.wait()
    finally:
        if disposition["action"] == "handover":
            supervisor.handover()
        else:
            supervisor.stop()
    print("fleet {}".format(
        "handed over (children still serving)"
        if disposition["action"] == "handover" else "stopped"),
        flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
