#!/usr/bin/env python3
"""perf_analyzer CLI: measure a model's serving performance to
stability and report a table + BENCH-schema JSON rows.

Python port of the reference perf_analyzer front door
(perf_analyzer.cc): pick a client backend, a load mode (concurrency
sweep, request-rate sweep, or token-streaming generation), and a
measurement config; the harness drives load, waits for 3 consecutive
stable windows per level, and reports client percentiles plus the
server-side queue/compute breakdown.

Examples:

    # in-process (no sockets): isolate model cost from transport
    python tools/perf_analyzer.py -m simple --backend inprocess \
        --concurrency-range 1:4

    # against a live server
    python tools/perf_analyzer.py -m simple --backend http \
        -u 127.0.0.1:8000 --concurrency-range 1:8:2

    # open-loop Poisson arrivals
    python tools/perf_analyzer.py -m simple --backend inprocess \
        --request-rate-range 100:400:100 --request-distribution poisson

    # token-level generation metrics (TTFT / ITL / tokens/sec)
    python tools/perf_analyzer.py -m llama_generate --backend inprocess \
        --generation --concurrency-range 1:4 --max-tokens 16

SIGINT is two-stage (reference perf_analyzer.cc:39-53): the first ^C
finishes the current window and reports the partial results (exit 0);
a second ^C aborts immediately (exit nonzero).
"""

import argparse
import json
import os
import signal
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "src", "python"))

EARLY_EXIT = threading.Event()
_SIGINTS = [0]


def _sigint_handler(signum, frame):
    _SIGINTS[0] += 1
    if _SIGINTS[0] == 1:
        EARLY_EXIT.set()
        print("\ncaught SIGINT: finishing the current window and "
              "reporting partial results (^C again to abort)",
              file=sys.stderr, flush=True)
    else:
        print("\nsecond SIGINT: aborting", file=sys.stderr, flush=True)
        os._exit(2)


def build_parser():
    ap = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("-m", "--model", required=True,
                    help="model to profile")
    ap.add_argument("--backend", default="http",
                    choices=["http", "grpc", "inprocess", "pool"],
                    help="client backend (default http)")
    ap.add_argument("-u", "--url", default="127.0.0.1:8000",
                    help="server host:port (http/grpc backends); an "
                         "http target may be a tools/router.py fleet "
                         "router, in which case per-level router "
                         "failover/handoff/shed counters land in the "
                         "report")
    ap.add_argument("--urls", default=None,
                    help="comma-separated replica URLs (pool backend)")
    ap.add_argument("--concurrency-range", default=None,
                    help="start:end[:step] closed-loop concurrency sweep")
    ap.add_argument("--request-rate-range", default=None,
                    help="start:end[:step] open-loop request/sec sweep")
    ap.add_argument("--request-distribution", default="constant",
                    choices=["constant", "poisson"],
                    help="inter-arrival distribution for rate mode")
    ap.add_argument("--measurement-interval", type=int, default=2000,
                    help="measurement window length in ms (default 2000)")
    ap.add_argument("--measurement-mode", default="time_windows",
                    choices=["time_windows", "count_windows"])
    ap.add_argument("--measurement-request-count", type=int, default=50,
                    help="completions per window in count_windows mode")
    ap.add_argument("--stability-percentage", type=float, default=10.0,
                    help="windows agree within this pct (default 10)")
    ap.add_argument("--max-trials", type=int, default=10,
                    help="max windows per level before giving up stable")
    ap.add_argument("-b", "--batch-size", type=int, default=1)
    ap.add_argument("--shape", action="append", default=[],
                    metavar="NAME:d1,d2,...",
                    help="pin a dynamic input dim (repeatable)")
    ap.add_argument("--input-const", action="append", default=[],
                    metavar="NAME:value",
                    help="fill an input with one fixed value instead "
                         "of random data (control knobs like DELAY_US; "
                         "repeatable)")
    ap.add_argument("--input-pool", type=int, default=16,
                    help="distinct random input sets rotated per context")
    ap.add_argument("--shared-memory", default="none",
                    choices=["none", "system", "xla"],
                    help="stage request tensors in shared memory "
                         "(reference InferDataManagerShm role): inputs "
                         "are written into created-and-registered "
                         "regions once, outside the timed path, and "
                         "requests carry {region, offset} references; "
                         "'xla' parks device segments too — against an "
                         "--backend inprocess server the resolve path "
                         "is zero-copy.  Generation mode adds a token "
                         "ring: responses shrink to slot descriptors "
                         "and TOKEN/LOGPROB land in the ring region")
    ap.add_argument("--output-shared-memory-size", type=int, default=0,
                    help="bytes reserved per declared output in a "
                         "shared output region; 0 (default) keeps "
                         "outputs in-band")
    ap.add_argument("--max-outstanding", type=int, default=512,
                    help="request-rate mode: backend executor/connection "
                         "capacity (the open-loop depth before the "
                         "schedule would queue client-side)")
    ap.add_argument("--warmup", type=float, default=0.3,
                    help="seconds of load before the first window")
    ap.add_argument("--seed", type=int, default=0)
    # generation mode
    ap.add_argument("--generation", action="store_true",
                    help="token-streaming mode: TTFT/ITL/tokens-sec")
    ap.add_argument("--max-tokens", type=int, default=16,
                    help="generation: tokens requested per stream")
    ap.add_argument("--prompt-len", type=int, default=8,
                    help="generation: synthetic prompt length")
    ap.add_argument("--shared-prefix-tokens", type=int, default=0,
                    help="generation: prepend ONE common prefix of N "
                         "tokens to every prompt (the shared-system-"
                         "prompt traffic shape of millions of users; "
                         "each prompt keeps its own --prompt-len "
                         "unique suffix).  The report's prefix-hit%% "
                         "column, window-diffed from the target's "
                         "/metrics, shows how much of it the radix "
                         "prefix cache absorbed")
    # in-process server construction
    ap.add_argument("--llama-slots", type=int, default=None,
                    help="inprocess generation: continuous-batching "
                         "slots (default: the max swept concurrency)")
    # distributed multi-process mode (perfanalyzer.coordinator — the
    # reference's MPI-barrier coordination, SURVEY §2.2, over a
    # localhost socket control channel)
    ap.add_argument("--workers", type=int, default=0,
                    help="fork N perf_analyzer worker processes, each "
                         "pinned round-robin to one of --urls (or all "
                         "driving -u, e.g. a fleet router); "
                         "barrier-synchronized windows, ONE merged "
                         "report (throughput = sum of worker "
                         "inferences, percentiles from merged raw "
                         "samples)")
    ap.add_argument("--windows", type=int, default=3,
                    help="distributed mode: synchronized measurement "
                         "windows per run (default 3)")
    ap.add_argument("--report-csv", default=None,
                    help="distributed mode: per-window CSV in the "
                         "reference report_writer schema")
    ap.add_argument("--worker-connect", default=None,
                    help=argparse.SUPPRESS)  # the spawned child mode
    ap.add_argument("--worker-id", type=int, default=0,
                    help=argparse.SUPPRESS)
    # output
    ap.add_argument("--csv", default=None, help="write CSV here")
    ap.add_argument("--json", default=None,
                    help="write JSON rows here (also printed to stdout)")
    ap.add_argument("-v", "--verbose", action="store_true")
    return ap


def parse_shapes(entries):
    shapes = {}
    for entry in entries:
        name, _, dims = entry.partition(":")
        if not dims:
            raise SystemExit(
                "--shape wants NAME:d1,d2,... (got {!r})".format(entry))
        shapes[name] = [int(d) for d in dims.split(",")]
    return shapes


def parse_consts(entries):
    consts = {}
    for entry in entries:
        name, _, value = entry.partition(":")
        if not value:
            raise SystemExit(
                "--input-const wants NAME:value (got {!r})".format(entry))
        try:
            consts[name] = int(value)
        except ValueError:
            try:
                consts[name] = float(value)
            except ValueError:
                consts[name] = value
    return consts


def build_inprocess_core(args, levels):
    """An in-process InferenceServer shaped for the requested profile
    (the analogue of the reference's Triton C-API backend server)."""
    from tpuserver.core import InferenceServer

    if args.generation or args.model == "llama_generate":
        from tpuserver.models import llama
        from tpuserver.models.llama_serving import LlamaGenerateModel

        slots = args.llama_slots or max(levels)
        need = (args.shared_prefix_tokens + args.prompt_len
                + args.max_tokens + 8)
        # the paged KV pool wants page_size (16) | max_seq
        max_seq = -(-max(64, need) // 16) * 16
        model = LlamaGenerateModel(
            cfg=llama.tiny(vocab=256), max_seq=max_seq,
            max_slots=slots)
        core = InferenceServer([model])
        model.warmup()
        return core
    from tpuserver.models import default_models

    return InferenceServer(default_models())


def build_generation_pool(metadata, args, seed=None, shared_seed=None):
    """Prompt pool for generation mode: DISTINCT random prompts per
    stream; MAX_TOKENS pinned from the CLI.  With
    ``--shared-prefix-tokens N`` every prompt carries the SAME leading
    N tokens (seeded independently of the pool index) ahead of its
    unique suffix — the shared-system-prompt shape the radix prefix
    cache and the router's prefix-affinity signal exist for.

    Distributed workers pass ``seed`` offset per worker (no two
    workers replay the same suffix stream) while leaving
    ``shared_seed`` at the run's base, so the shared system prompt is
    the SAME across the whole worker fleet — what makes the merged
    prefix-hit%% a fleet number."""
    import numpy as np

    if seed is None:
        seed = args.seed
    if shared_seed is None:
        shared_seed = args.seed + 7777
    shared = None
    if args.shared_prefix_tokens > 0:
        shared = np.random.RandomState(shared_seed).randint(
            1, 200, size=(args.shared_prefix_tokens,)).astype(np.int32)
    pool = []
    for i in range(args.input_pool):
        rng = np.random.RandomState(seed + i)
        inputs = {}
        for spec in metadata.get("inputs", []):
            name = spec["name"]
            if name.upper() == "MAX_TOKENS":
                inputs[name] = np.array([args.max_tokens], dtype=np.int32)
            elif any(int(d) < 0 for d in spec["shape"]):
                # dynamic prompt axis: synthesize at --prompt-len with
                # small ids (valid for every vocab the zoo uses)
                suffix = rng.randint(
                    1, 200, size=(args.prompt_len,)).astype(np.int32)
                inputs[name] = (
                    np.concatenate([shared, suffix])
                    if shared is not None else suffix)
            else:
                dims = [int(d) for d in spec["shape"]]
                inputs[name] = rng.randint(
                    1, 200, size=dims).astype(np.int32)
        pool.append(inputs)
    return pool


def run_worker(args):
    """Hidden child mode (``--worker-connect``): one worker process of
    a distributed run.  Drives closed-loop concurrency against its
    pinned replica (``--urls`` round-robined by ``--worker-id``, else
    ``-u``) continuously, and measures exactly the windows the
    coordinator's barrier releases — raw latency records ship back so
    the parent merges samples, never percentiles."""
    from perfanalyzer.client_backend import build_input_pool, create_backend
    from perfanalyzer.coordinator import WorkerChannel
    from perfanalyzer.load_manager import ConcurrencyManager
    from perfanalyzer.profiler import parse_range

    level = parse_range(args.concurrency_range or "1")[0]
    urls = ([u.strip() for u in args.urls.split(",") if u.strip()]
            if args.urls else [args.url])
    url = urls[args.worker_id % len(urls)]
    backend = create_backend("http", url=url, max_inflight=level)
    manager = None
    channel = None
    shm = None
    gen_profiler = None
    try:
        metadata = backend.model_metadata(args.model)
        if args.generation:
            from perfanalyzer.generation import GenerationProfiler

            # per-worker suffix stream, run-wide shared prefix (see
            # build_generation_pool): the merged prefix-hit%% is a
            # fleet number, not N private caches
            pool = build_generation_pool(
                metadata, args, seed=args.seed + 1000 * args.worker_id,
                shared_seed=args.seed + 7777)
            gen_profiler = GenerationProfiler(
                backend, args.model, pool,
                measurement_interval_s=args.measurement_interval / 1000.0,
                early_exit=EARLY_EXIT)
            gen_profiler.change_level(level)
            collector = gen_profiler.collector
            # warmup gate before saying hello: the first barrier
            # window must not eat this worker's cold-start (XLA
            # compiles, cold prefix caches land outside measurement)
            gate = time.monotonic() + 120.0
            while (collector.lifetime_generations() == 0
                   and time.monotonic() < gate
                   and not EARLY_EXIT.is_set()):
                time.sleep(0.02)
            channel = WorkerChannel(args.worker_connect, args.worker_id)

            def run_gen_window(duration_s, index):
                collector.start_window()
                t0 = time.perf_counter()
                deadline = t0 + duration_s
                while True:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0 or EARLY_EXIT.is_set():
                        break
                    time.sleep(min(0.05, remaining))
                duration = time.perf_counter() - t0
                window = collector.end_window()
                # raw TTFT/ITL samples ship to the parent — the merge
                # pools samples, never percentiles (same rule as the
                # scalar latencies_s)
                return {"completed": window["generations"],
                        "errors": window["errors"],
                        "duration_s": duration,
                        "latencies_s": [],
                        "tokens": window["tokens"],
                        "ttfts_s": window["ttfts_s"],
                        "itls_s": window["itls_s"],
                        "generations": window["generations"],
                        "resumed_streams": window["resumed_streams"],
                        "resume_events": window["resume_events"]}

            channel.serve(run_gen_window)
            return 0
        config = backend.model_config(args.model)
        pool = build_input_pool(
            metadata, config,
            pool_size=args.input_pool,
            batch_size=args.batch_size,
            shape_overrides=parse_shapes(args.shape),
            const_overrides=parse_consts(args.input_const),
            # distinct per-worker streams of inputs: no two workers
            # replay the same request sequence in lockstep
            seed=args.seed + 1000 * args.worker_id)
        if args.shared_memory != "none":
            # per-worker region lifecycle: every worker process creates
            # and registers its OWN regions (names carry its pid tag),
            # and tears exactly those down on exit — N workers against
            # one server never collide or leak
            from perfanalyzer.client_backend import ShmInferDataManager

            shm = ShmInferDataManager(
                backend, args.shared_memory,
                tag="w{}".format(args.worker_id))
            refs = shm.stage_input_sets(pool)
            out_refs = None
            if args.output_shared_memory_size > 0:
                out_refs = shm.stage_outputs(
                    [o["name"] for o in metadata.get("outputs", [])],
                    args.output_shared_memory_size)
            prepared = backend.prepare_shm(args.model, refs, out_refs)
        else:
            prepared = backend.prepare(args.model, pool)
        manager = ConcurrencyManager(backend, args.model, prepared)
        manager.change_level(level)
        collector = manager.collector
        channel = WorkerChannel(args.worker_connect, args.worker_id)

        def run_window(duration_s, index):
            collector.start_window()
            t0 = time.perf_counter()
            deadline = t0 + duration_s
            while True:
                remaining = deadline - time.perf_counter()
                if remaining <= 0 or EARLY_EXIT.is_set():
                    break
                time.sleep(min(0.05, remaining))
            duration = time.perf_counter() - t0
            latencies, errors = collector.end_window()
            # tokens is part of the window-result contract; scalar
            # workers always send 0 (generation-mode workers are the
            # ROADMAP item-5 leftover that will fill it)
            return {"completed": len(latencies), "errors": errors,
                    "duration_s": duration, "latencies_s": latencies,
                    "tokens": 0}

        channel.serve(run_window)
    finally:
        if channel is not None:
            channel.close()
        if manager is not None:
            manager.stop()
        if gen_profiler is not None:
            gen_profiler.stop()
        if shm is not None:
            shm.close()
        backend.close()
    return 0


def _prefix_snapshot_with_grace(probe, grace_s=3.0):
    """One ``/metrics`` prefix-counter snapshot, re-polled briefly
    when the families are absent.  Against a router the counters are
    the fleet aggregate, and its fold for a scrape round that found
    NO live replica (a chaos campaign's zero-capacity window, or
    every replica still booting) carries no prefix families — a
    single-shot probe landing in that window would drop the
    prefix-hit%% column from the whole run."""
    deadline = time.monotonic() + grace_s
    snap = probe.prefix_cache_snapshot()
    while snap is None and time.monotonic() < deadline:
        if EARLY_EXIT.wait(0.1):
            break
        snap = probe.prefix_cache_snapshot()
    return snap


def run_coordinator(args):
    """Parent mode (``--workers N``): fork N worker processes, run
    barrier-synchronized windows, merge, and emit ONE report."""
    import subprocess

    from perfanalyzer.coordinator import (
        Coordinator,
        merge_windows,
        reap_workers,
    )
    from perfanalyzer.profiler import ProfileResult, parse_range
    from perfanalyzer.report import ReportWriter

    if args.request_rate_range:
        raise SystemExit(
            "--workers drives the closed-loop modes; the request-rate "
            "mode is single-process")
    if args.generation and args.shared_memory != "none":
        raise SystemExit(
            "--workers --generation is in-band only; drop "
            "--shared-memory (token rings are a direct-replica mode)")
    if args.backend not in ("http",):
        raise SystemExit(
            "--workers spawns http worker processes; --backend {} is "
            "single-process".format(args.backend))
    levels = parse_range(args.concurrency_range or "1")
    if len(levels) != 1:
        raise SystemExit(
            "--workers measures ONE concurrency level per run "
            "(got sweep {})".format(levels))
    level = levels[0]
    window_s = args.measurement_interval / 1000.0
    dist_mode = ("distributed_generation" if args.generation
                 else "distributed_concurrency")
    coord = Coordinator(args.workers).listen()
    print("*** Measurement Settings ***\n"
          "  model: {}  backend: http  mode: {}\n"
          "  workers: {}  concurrency/worker: {}  windows: {} x {} ms "
          "(barrier-synchronized)".format(
              args.model, dist_mode, args.workers, level, args.windows,
              args.measurement_interval), flush=True)
    argv = [sys.executable, os.path.abspath(__file__),
            "-m", args.model, "--backend", "http", "-u", args.url,
            "--concurrency-range", str(level),
            "--input-pool", str(args.input_pool),
            "-b", str(args.batch_size), "--seed", str(args.seed),
            "--shared-memory", args.shared_memory,
            "--output-shared-memory-size",
            str(args.output_shared_memory_size)]
    if args.urls:
        argv += ["--urls", args.urls]
    if args.generation:
        argv += ["--generation",
                 "--max-tokens", str(args.max_tokens),
                 "--prompt-len", str(args.prompt_len),
                 "--shared-prefix-tokens",
                 str(args.shared_prefix_tokens)]
    for entry in args.shape:
        argv += ["--shape", entry]
    for entry in args.input_const:
        argv += ["--input-const", entry]
    procs = []
    window_rows = []
    # fleet prefix-hit%% is parent-side: one probe backend reads the
    # target's /metrics prefix counters (the churn-safe fleet
    # aggregate when -u fronts a router) before/after the windows
    prefix_before = prefix_after = None
    probe = None
    if args.generation:
        from perfanalyzer.client_backend import create_backend

        probe = create_backend("http", url=args.url, max_inflight=1)
    try:
        for i in range(args.workers):
            procs.append(subprocess.Popen(
                argv + ["--worker-connect", coord.address,
                        "--worker-id", str(i)]))
        coord.wait_for_workers(timeout_s=120.0)
        if args.warmup > 0:
            # load is already flowing (workers start their managers
            # before dialing in); the parent just waits it out
            EARLY_EXIT.wait(args.warmup)
        if probe is not None:
            # post-warmup baseline, like the single-process profiler:
            # compile-time/cold admissions stay out of the hit rate.
            # Re-polled briefly when the column is absent: under chaos
            # a zero-capacity window (every replica killed at once)
            # can make the router's aggregate fold come up empty, and
            # one None here silently costs the whole run its
            # prefix-hit%% column
            prefix_before = _prefix_snapshot_with_grace(probe)
        for index in range(args.windows):
            if EARLY_EXIT.is_set():
                break
            row = coord.run_window(index, window_s)
            row["concurrency"] = level * args.workers
            if row.get("tokens") and row["duration_s"] > 0:
                row["tokens_per_sec"] = row["tokens"] / row["duration_s"]
            window_rows.append(row)
            if args.verbose:
                print("  window {:2d}: {:8.1f} infer/sec over {} "
                      "workers".format(index + 1, row["throughput"],
                                       row["workers"]), flush=True)
        if probe is not None:
            prefix_after = _prefix_snapshot_with_grace(probe)
    finally:
        coord.shutdown()
        reap_workers(procs)
        if probe is not None:
            probe.close()
    if not window_rows:
        print(json.dumps({"error": "no synchronized windows completed"}),
              flush=True)
        return 1
    merged = merge_windows(window_rows)
    result = ProfileResult(
        mode=dist_mode,
        level=level * args.workers,
        stable=True,
        interrupted=EARLY_EXIT.is_set(),
        trials=len(window_rows),
        workers=args.workers,
    )
    result.update(merged)
    if args.generation:
        from perfanalyzer import metrics as _metrics

        # token-rate throughput + TTFT/ITL percentiles over the POOLED
        # raw samples of every worker and window — the same report
        # columns the single-process generation profiler emits, at
        # fleet scale (raw sample lists dropped from the report)
        duration = merged.get("duration_s", 0.0)
        result["throughput"] = (
            merged.get("tokens", 0) / duration if duration > 0 else 0.0)
        result["generations"] = merged.get("generations", 0)
        result["gen_per_sec"] = (
            merged.get("generations", 0) / duration
            if duration > 0 else 0.0)
        ttfts = result.pop("ttfts_s", None) or []
        itls = result.pop("itls_s", None) or []
        for prefix_key, sample in (("ttft", ttfts), ("itl", itls)):
            if sample:
                ms = sorted(v * 1e3 for v in sample)
                result[prefix_key + "_avg_ms"] = sum(ms) / len(ms)
                for p in (50, 90, 95, 99):
                    result["{}_p{}_ms".format(prefix_key, p)] = (
                        _metrics.percentile(ms, p, presorted=True))
            else:
                result[prefix_key + "_avg_ms"] = None
                for p in (50, 90, 95, 99):
                    result["{}_p{}_ms".format(prefix_key, p)] = None
        if prefix_before is not None and prefix_after is not None:
            dh = max(0, prefix_after["hits"] - prefix_before["hits"])
            dm = max(0, prefix_after["misses"] - prefix_before["misses"])
            result["prefix_cache_hits"] = dh
            result["prefix_cache_misses"] = dm
            result["prefix_hit_pct"] = (
                100.0 * dh / (dh + dm) if dh + dm else None)
    writer = ReportWriter(
        args.model, "http-x{}".format(args.workers),
        extra_tags={"early_exit": True} if EARLY_EXIT.is_set() else None)
    writer.print_table([result])
    print()
    writer.print_json([result])
    if args.csv:
        writer.write_csv(args.csv, [result])
    if args.json:
        writer.write_json(args.json, [result])
    if args.report_csv:
        writer.write_window_csv(args.report_csv, window_rows)
    return 0


def main(argv=None):
    args = build_parser().parse_args(argv)
    signal.signal(signal.SIGINT, _sigint_handler)

    if args.worker_connect:
        return run_worker(args)
    if args.workers:
        return run_coordinator(args)

    from perfanalyzer.client_backend import build_input_pool, create_backend
    from perfanalyzer.generation import GenerationProfiler
    from perfanalyzer.load_manager import (
        ConcurrencyManager,
        RequestRateManager,
    )
    from perfanalyzer.profiler import InferenceProfiler, parse_range
    from perfanalyzer.report import ReportWriter

    if args.concurrency_range and args.request_rate_range:
        raise SystemExit(
            "--concurrency-range and --request-rate-range are mutually "
            "exclusive")
    if args.generation and args.request_rate_range:
        raise SystemExit(
            "generation mode is concurrency-based (N worker streams); "
            "--request-rate-range is not supported with --generation")
    rate_mode = bool(args.request_rate_range)
    levels = parse_range(
        args.request_rate_range or args.concurrency_range or "1")

    core = None
    if args.backend == "inprocess":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        core = build_inprocess_core(args, levels)
    backend = create_backend(
        args.backend,
        url=args.url,
        urls=args.urls.split(",") if args.urls else None,
        core=core,
        # size the backend for the load it must carry: swept
        # concurrency (closed loop) or the open-loop outstanding depth
        max_inflight=(args.max_outstanding if rate_mode
                      else max(levels)),
    )

    interval_s = args.measurement_interval / 1000.0
    mode = ("generation" if args.generation
            else "request_rate" if rate_mode else "concurrency")
    print("*** Measurement Settings ***\n"
          "  model: {}  backend: {}  mode: {}\n"
          "  levels: {}  window: {} ms ({})  stability: {}% over 3 "
          "windows, max {} trials".format(
              args.model, args.backend, mode, levels,
              args.measurement_interval, args.measurement_mode,
              args.stability_percentage, args.max_trials), flush=True)
    if args.shared_memory != "none" and args.backend == "pool":
        raise SystemExit(
            "--shared-memory drives the http/grpc/inprocess backends; "
            "the pool backend is in-band only")

    manager = None
    shm = None
    try:
        from perfanalyzer.client_backend import ShmInferDataManager

        metadata = backend.model_metadata(args.model)
        if args.shared_memory != "none":
            shm = ShmInferDataManager(backend, args.shared_memory)
        if args.generation:
            pool = build_generation_pool(metadata, args)
            gen_params = None
            if shm is not None:
                # prompts stage once into a shm region (requests carry
                # references); every stream gets its own token-ring
                # lane, so concurrent generations never share slots
                refs = shm.stage_input_sets(
                    [{"PROMPT_IDS": s["PROMPT_IDS"]} for s in pool])
                pool = [dict(s, PROMPT_IDS=r["PROMPT_IDS"])
                        for s, r in zip(pool, refs)]
                import itertools

                lanes = 2 * max(levels)
                slots = max(1, args.max_tokens)
                lane_bytes = slots * 8
                ring_name, _ = shm.create_region(
                    "ring", lanes * lane_bytes)
                counter = itertools.count()
                lane_lock = threading.Lock()

                def gen_params():
                    with lane_lock:
                        lane = next(counter) % lanes
                    return {"shm_ring_region": ring_name,
                            "shm_ring_slots": slots,
                            "shm_ring_offset": lane * lane_bytes}

            profiler = GenerationProfiler(
                backend, args.model, pool,
                parameters=gen_params,
                measurement_interval_s=interval_s,
                stability_pct=args.stability_percentage,
                max_trials=args.max_trials,
                warmup_s=args.warmup,
                early_exit=EARLY_EXIT,
                verbose=args.verbose)
        else:
            config = backend.model_config(args.model)
            pool = build_input_pool(
                metadata, config,
                pool_size=args.input_pool,
                batch_size=args.batch_size,
                shape_overrides=parse_shapes(args.shape),
                const_overrides=parse_consts(args.input_const),
                seed=args.seed)
            if shm is not None:
                refs = shm.stage_input_sets(pool)
                out_refs = None
                if args.output_shared_memory_size > 0:
                    out_refs = shm.stage_outputs(
                        [o["name"]
                         for o in metadata.get("outputs", [])],
                        args.output_shared_memory_size)
                prepared = backend.prepare_shm(
                    args.model, refs, out_refs)
            else:
                prepared = backend.prepare(args.model, pool)
            if rate_mode:
                manager = RequestRateManager(
                    backend, args.model, prepared,
                    distribution=args.request_distribution,
                    seed=args.seed)
            else:
                manager = ConcurrencyManager(
                    backend, args.model, prepared)
            profiler = InferenceProfiler(
                backend, args.model, manager,
                measurement_mode=args.measurement_mode,
                measurement_interval_s=interval_s,
                measurement_request_count=args.measurement_request_count,
                stability_pct=args.stability_percentage,
                max_trials=args.max_trials,
                # open-loop latencies trend with queue depth by design;
                # judge rate-mode stability on throughput alone (the
                # reference's request-rate exemption)
                check_latency_stability=not rate_mode,
                warmup_s=args.warmup,
                early_exit=EARLY_EXIT,
                verbose=args.verbose)
        results = profiler.sweep(levels)
    finally:
        if manager is not None:
            manager.stop()
        if shm is not None:
            # the per-worker region lifecycle: unregister on the
            # server, unlink the client windows
            shm.close()
        backend.close()
        if core is not None:
            core.close()

    if not results:
        print(json.dumps({"error": "no measurements completed"}),
              flush=True)
        return 1
    writer = ReportWriter(
        args.model, args.backend,
        extra_tags={"early_exit": True} if EARLY_EXIT.is_set() else None)
    writer.print_table(results)
    print()
    writer.print_json(results)
    if args.csv:
        writer.write_csv(args.csv, results)
    if args.json:
        writer.write_json(args.json, results)
    unstable = [r["level"] for r in results if not r["stable"]]
    if unstable and not EARLY_EXIT.is_set():
        print("warning: levels {} never reached {}% stability within "
              "{} trials; numbers reported from the last {} windows"
              .format(unstable, args.stability_percentage,
                      args.max_trials, 3),
              file=sys.stderr, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
