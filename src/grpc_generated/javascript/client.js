// Minimal Node.js gRPC client for the KServe v2 protocol using dynamic
// proto loading (role of reference src/grpc_generated/javascript/
// client.js:27-33).
//
//   npm install @grpc/grpc-js @grpc/proto-loader
//   node client.js [host:port]

const grpc = require("@grpc/grpc-js");
const protoLoader = require("@grpc/proto-loader");
const path = require("path");

const url = process.argv[2] || "localhost:8001";
const PROTO_DIR = path.join(__dirname, "..", "..", "..", "proto");

const definition = protoLoader.loadSync(
  path.join(PROTO_DIR, "grpc_service.proto"),
  { includeDirs: [PROTO_DIR], keepCase: true, longs: Number }
);
const inference = grpc.loadPackageDefinition(definition).inference;
const client = new inference.GRPCInferenceService(
  url, grpc.credentials.createInsecure()
);

function int32ToLE(values) {
  const buf = Buffer.alloc(values.length * 4);
  values.forEach((v, i) => buf.writeInt32LE(v, i * 4));
  return buf;
}

function leToInt32(buf) {
  const out = [];
  for (let i = 0; i < buf.length; i += 4) {
    out.push(buf.readInt32LE(i));
  }
  return out;
}

client.ServerLive({}, (err, response) => {
  if (err || !response.live) {
    console.error("server not live:", err);
    process.exit(1);
  }
  const input0 = Array.from({ length: 16 }, (_, i) => i);
  const input1 = Array.from({ length: 16 }, () => 1);
  const request = {
    model_name: "simple",
    inputs: [
      { name: "INPUT0", datatype: "INT32", shape: [1, 16] },
      { name: "INPUT1", datatype: "INT32", shape: [1, 16] },
    ],
    raw_input_contents: [int32ToLE(input0), int32ToLE(input1)],
  };
  client.ModelInfer(request, (err, response) => {
    if (err) {
      console.error("infer failed:", err);
      process.exit(1);
    }
    const sums = leToInt32(response.raw_output_contents[0]);
    const diffs = leToInt32(response.raw_output_contents[1]);
    for (let i = 0; i < 16; i++) {
      if (sums[i] !== input0[i] + input1[i] ||
          diffs[i] !== input0[i] - input1[i]) {
        console.error("wrong result at", i);
        process.exit(1);
      }
    }
    console.log("PASS: js infer");
  });
});
