// Minimal Java gRPC client using stubs generated from the repo's proto
// files (role of reference src/grpc_generated/java/SimpleJavaClient.java).
//
// Generate stubs with the protobuf-gradle-plugin or:
//   protoc --java_out=. --grpc-java_out=. -I ../../../proto \
//       grpc_service.proto model_config.proto
// (needs protoc-gen-grpc-java), then compile against grpc-netty-shaded,
// grpc-protobuf and grpc-stub.

import inference.GRPCInferenceServiceGrpc;
import inference.GrpcService.InferTensorContents;
import inference.GrpcService.ModelInferRequest;
import inference.GrpcService.ModelInferResponse;
import inference.GrpcService.ServerLiveRequest;
import io.grpc.ManagedChannel;
import io.grpc.ManagedChannelBuilder;
import java.nio.ByteBuffer;
import java.nio.ByteOrder;
import com.google.protobuf.ByteString;

public class SimpleJavaClient {
  public static void main(String[] args) {
    String target = args.length > 0 ? args[0] : "localhost:8001";
    ManagedChannel channel =
        ManagedChannelBuilder.forTarget(target).usePlaintext().build();
    GRPCInferenceServiceGrpc.GRPCInferenceServiceBlockingStub stub =
        GRPCInferenceServiceGrpc.newBlockingStub(channel);

    boolean live =
        stub.serverLive(ServerLiveRequest.getDefaultInstance()).getLive();
    if (!live) {
      System.err.println("server not live");
      System.exit(1);
    }

    int[] input0 = new int[16];
    int[] input1 = new int[16];
    ByteBuffer raw0 =
        ByteBuffer.allocate(64).order(ByteOrder.LITTLE_ENDIAN);
    ByteBuffer raw1 =
        ByteBuffer.allocate(64).order(ByteOrder.LITTLE_ENDIAN);
    for (int i = 0; i < 16; i++) {
      input0[i] = i;
      input1[i] = 1;
      raw0.putInt(input0[i]);
      raw1.putInt(input1[i]);
    }

    ModelInferRequest request =
        ModelInferRequest.newBuilder()
            .setModelName("simple")
            .addInputs(
                ModelInferRequest.InferInputTensor.newBuilder()
                    .setName("INPUT0")
                    .setDatatype("INT32")
                    .addShape(1)
                    .addShape(16))
            .addInputs(
                ModelInferRequest.InferInputTensor.newBuilder()
                    .setName("INPUT1")
                    .setDatatype("INT32")
                    .addShape(1)
                    .addShape(16))
            .addRawInputContents(ByteString.copyFrom(raw0.array()))
            .addRawInputContents(ByteString.copyFrom(raw1.array()))
            .build();

    ModelInferResponse response = stub.modelInfer(request);
    ByteBuffer sums =
        response.getRawOutputContents(0).asReadOnlyByteBuffer()
            .order(ByteOrder.LITTLE_ENDIAN);
    ByteBuffer diffs =
        response.getRawOutputContents(1).asReadOnlyByteBuffer()
            .order(ByteOrder.LITTLE_ENDIAN);
    for (int i = 0; i < 16; i++) {
      if (sums.getInt() != input0[i] + input1[i]
          || diffs.getInt() != input0[i] - input1[i]) {
        System.err.println("wrong result at " + i);
        System.exit(1);
      }
    }
    System.out.println("PASS: java grpc infer");
    channel.shutdownNow();
  }
}
