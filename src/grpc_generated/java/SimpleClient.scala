// Minimal Scala gRPC client over the same generated Java stubs (role of
// reference src/grpc_generated/java/.../SimpleClient.scala).  Build the
// stubs as described in SimpleJavaClient.java, add scala-library.

import com.google.protobuf.ByteString
import inference.GRPCInferenceServiceGrpc
import inference.GrpcService.{ModelInferRequest, ServerLiveRequest}
import io.grpc.ManagedChannelBuilder
import java.nio.{ByteBuffer, ByteOrder}

object SimpleClient {
  def main(args: Array[String]): Unit = {
    val target = if (args.nonEmpty) args(0) else "localhost:8001"
    val channel =
      ManagedChannelBuilder.forTarget(target).usePlaintext().build()
    val stub = GRPCInferenceServiceGrpc.newBlockingStub(channel)

    require(
      stub.serverLive(ServerLiveRequest.getDefaultInstance).getLive,
      "server not live")

    val input0 = (0 until 16).map(_.toInt)
    val input1 = Seq.fill(16)(1)
    def pack(values: Seq[Int]): ByteString = {
      val buf =
        ByteBuffer.allocate(values.size * 4).order(ByteOrder.LITTLE_ENDIAN)
      values.foreach(buf.putInt)
      ByteString.copyFrom(buf.array())
    }

    def tensor(name: String) =
      ModelInferRequest.InferInputTensor
        .newBuilder()
        .setName(name)
        .setDatatype("INT32")
        .addShape(1)
        .addShape(16)

    val request = ModelInferRequest
      .newBuilder()
      .setModelName("simple")
      .addInputs(tensor("INPUT0"))
      .addInputs(tensor("INPUT1"))
      .addRawInputContents(pack(input0))
      .addRawInputContents(pack(input1))
      .build()

    val response = stub.modelInfer(request)
    val sums = response
      .getRawOutputContents(0)
      .asReadOnlyByteBuffer()
      .order(ByteOrder.LITTLE_ENDIAN)
    (0 until 16).foreach { i =>
      require(sums.getInt() == input0(i) + input1(i), s"wrong sum at $i")
    }
    println("PASS: scala grpc infer")
    channel.shutdownNow()
  }
}
