// Minimal Go gRPC client for the KServe v2 protocol (role of reference
// src/grpc_generated/go/grpc_simple_client.go).  Generate the stubs from
// the repo's proto files first:
//
//	protoc --go_out=. --go-grpc_out=. -I ../../../proto \
//	    grpc_service.proto model_config.proto
//
// then: go mod init client && go mod tidy && go run grpc_simple_client.go
package main

import (
	"bytes"
	"context"
	"encoding/binary"
	"flag"
	"log"
	"time"

	"google.golang.org/grpc"
	"google.golang.org/grpc/credentials/insecure"

	pb "client/inference" // generated from proto/grpc_service.proto
)

func int32sToLE(values []int32) []byte {
	buf := new(bytes.Buffer)
	for _, v := range values {
		binary.Write(buf, binary.LittleEndian, v)
	}
	return buf.Bytes()
}

func leToInt32s(raw []byte) []int32 {
	out := make([]int32, len(raw)/4)
	for i := range out {
		out[i] = int32(binary.LittleEndian.Uint32(raw[i*4:]))
	}
	return out
}

func main() {
	url := flag.String("u", "localhost:8001", "server host:port")
	flag.Parse()

	conn, err := grpc.NewClient(
		*url, grpc.WithTransportCredentials(insecure.NewCredentials()))
	if err != nil {
		log.Fatalf("connect: %v", err)
	}
	defer conn.Close()
	client := pb.NewGRPCInferenceServiceClient(conn)
	ctx, cancel := context.WithTimeout(context.Background(), 30*time.Second)
	defer cancel()

	live, err := client.ServerLive(ctx, &pb.ServerLiveRequest{})
	if err != nil || !live.Live {
		log.Fatalf("server not live: %v", err)
	}

	input0 := make([]int32, 16)
	input1 := make([]int32, 16)
	for i := range input0 {
		input0[i] = int32(i)
		input1[i] = 1
	}
	request := &pb.ModelInferRequest{
		ModelName: "simple",
		Inputs: []*pb.ModelInferRequest_InferInputTensor{
			{Name: "INPUT0", Datatype: "INT32", Shape: []int64{1, 16}},
			{Name: "INPUT1", Datatype: "INT32", Shape: []int64{1, 16}},
		},
		RawInputContents: [][]byte{
			int32sToLE(input0), int32sToLE(input1),
		},
	}
	response, err := client.ModelInfer(ctx, request)
	if err != nil {
		log.Fatalf("infer: %v", err)
	}
	sums := leToInt32s(response.RawOutputContents[0])
	diffs := leToInt32s(response.RawOutputContents[1])
	for i := range input0 {
		if sums[i] != input0[i]+input1[i] ||
			diffs[i] != input0[i]-input1[i] {
			log.Fatalf("wrong result at %d", i)
		}
	}
	log.Println("PASS: go infer")
}
