#!/usr/bin/env python3
"""Wheel packaging for the TPU-native Triton client stack (role of
reference src/python/library/setup.py:60-80: extras ``grpc``/``http``/
``all``, bundled native shm library, deprecated shim packages).

Build:  cd src/python && python setup.py bdist_wheel
        (or: python build_wheel.py --dest-dir ../../dist)
The native POSIX-shm shim (libcshm.so) auto-compiles on first import
when absent, so the wheel works from source checkouts too; build_wheel.py
pre-compiles and bundles it.
"""

import os

from setuptools import find_packages, setup

VERSION = os.environ.get("VERSION", "0.1.0")

REQUIRES_HTTP = []  # stdlib-socket HTTP client: no extra deps
REQUIRES_GRPC = ["grpcio>=1.41", "protobuf>=3.18"]
REQUIRES_AIO = ["aiohttp>=3.8"]

this_dir = os.path.dirname(os.path.abspath(__file__))


def package_data():
    data = {"tritonclient.utils.shared_memory": []}
    lib = os.path.join(
        this_dir, "tritonclient", "utils", "shared_memory", "libcshm.so"
    )
    if os.path.exists(lib):
        data["tritonclient.utils.shared_memory"].append("libcshm.so")
    return data


setup(
    name="tpu-tritonclient",
    version=VERSION,
    description=(
        "TPU-native client libraries for the KServe v2 / Triton "
        "inference protocol (HTTP + gRPC, system and XLA/TPU-HBM "
        "shared memory)"
    ),
    license="BSD",
    python_requires=">=3.9",
    packages=find_packages(
        include=[
            "tritonclient",
            "tritonclient.*",
            "tritonhttpclient",
            "tritongrpcclient",
            "tritonclientutils",
            "tritonshmutils",
            "tritonshmutils.*",
        ]
    ),
    package_data=package_data(),
    install_requires=["numpy>=1.20"],
    extras_require={
        "http": REQUIRES_HTTP + REQUIRES_AIO,
        "grpc": REQUIRES_GRPC,
        "all": REQUIRES_HTTP + REQUIRES_GRPC + REQUIRES_AIO,
    },
    zip_safe=False,
)
