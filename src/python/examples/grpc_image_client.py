#!/usr/bin/env python3
"""Image classification over gRPC using the raw generated service stubs
(no tritonclient wrapper) — shows direct protobuf assembly (role of
reference src/python/examples/grpc_image_client.py)."""

import argparse
import struct
import sys

import grpc
import numpy as np

from tritonclient.grpc import grpc_service_pb2 as pb
from tritonclient.grpc._service import METHODS, SERVICE


def _stub_call(channel, name, request, timeout=None):
    req_cls, resp_cls, kind = METHODS[name]
    method = channel.unary_unary(
        "/{}/{}".format(SERVICE, name),
        request_serializer=req_cls.SerializeToString,
        response_deserializer=resp_cls.FromString,
    )
    return method(request, timeout=timeout)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    parser.add_argument("-m", "--model-name", default="resnet50")
    parser.add_argument("-c", "--classes", type=int, default=1)
    parser.add_argument("--synthetic", type=int, default=1)
    args = parser.parse_args()

    channel = grpc.insecure_channel(args.url)

    live = _stub_call(channel, "ServerLive", pb.ServerLiveRequest())
    if not live.live:
        print("FAILED: server not live")
        sys.exit(1)

    metadata = _stub_call(
        channel, "ModelMetadata",
        pb.ModelMetadataRequest(name=args.model_name),
    )
    input_name = metadata.inputs[0].name
    output_name = metadata.outputs[0].name

    rng = np.random.RandomState(7)
    img = rng.rand(1, 224, 224, 3).astype(np.float32)

    request = pb.ModelInferRequest(model_name=args.model_name)
    tensor = request.inputs.add()
    tensor.name = input_name
    tensor.datatype = "FP32"
    tensor.shape.extend(img.shape)
    request.raw_input_contents.append(img.tobytes())
    out = request.outputs.add()
    out.name = output_name
    out.parameters["classification"].int64_param = args.classes

    response = _stub_call(channel, "ModelInfer", request, timeout=300)
    raw = response.raw_output_contents[0]
    # BYTES classification tensor: 4-byte little-endian length prefix per
    # element ("value:index:label")
    entries = []
    pos = 0
    while pos < len(raw):
        (length,) = struct.unpack_from("<I", raw, pos)
        pos += 4
        entries.append(raw[pos : pos + length].decode("utf-8"))
        pos += length
    if len(entries) != args.classes:
        print("FAILED: expected {} classes, got {}".format(
            args.classes, len(entries)))
        sys.exit(1)
    for entry in entries:
        print("    " + entry)
    channel.close()
    print("PASS: raw-stub image client")


if __name__ == "__main__":
    main()
