#!/usr/bin/env python3
"""BYTES-tensor infer on `simple_string` over HTTP: string-encoded
integers are added/subtracted server-side (role of reference
src/python/examples/simple_http_string_infer_client.py)."""

import argparse
import sys

import numpy as np

import tritonclient.http as httpclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-v", "--verbose", action="store_true")
    parser.add_argument("-u", "--url", default="localhost:8000")
    args = parser.parse_args()

    client = httpclient.InferenceServerClient(
        url=args.url, verbose=args.verbose
    )

    in0 = np.arange(16, dtype=np.int32)
    in1 = np.full(16, 1, dtype=np.int32)
    input0_str = np.array(
        [str(x).encode("utf-8") for x in in0], dtype=np.object_
    ).reshape(1, 16)
    input1_str = np.array(
        [str(x).encode("utf-8") for x in in1], dtype=np.object_
    ).reshape(1, 16)

    inputs = [
        httpclient.InferInput("INPUT0", [1, 16], "BYTES"),
        httpclient.InferInput("INPUT1", [1, 16], "BYTES"),
    ]
    inputs[0].set_data_from_numpy(input0_str, binary_data=True)
    inputs[1].set_data_from_numpy(input1_str, binary_data=False)
    outputs = [
        httpclient.InferRequestedOutput("OUTPUT0", binary_data=True),
        httpclient.InferRequestedOutput("OUTPUT1", binary_data=False),
    ]

    result = client.infer("simple_string", inputs, outputs=outputs)
    output0 = result.as_numpy("OUTPUT0").reshape(16)
    output1 = result.as_numpy("OUTPUT1").reshape(16)
    for i in range(16):
        if int(output0[i]) != in0[i] + in1[i]:
            print("error: incorrect sum")
            sys.exit(1)
        if int(output1[i]) != in0[i] - in1[i]:
            print("error: incorrect difference")
            sys.exit(1)
    client.close()
    print("PASS: string infer")


if __name__ == "__main__":
    main()
