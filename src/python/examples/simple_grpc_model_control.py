#!/usr/bin/env python3
"""Explicit model load/unload over gRPC (role of reference
src/python/examples/simple_grpc_model_control.py)."""

import argparse
import sys

import numpy as np

import tritonclient.grpc as grpcclient
from tritonclient.utils import InferenceServerException


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-v", "--verbose", action="store_true")
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    client = grpcclient.InferenceServerClient(
        url=args.url, verbose=args.verbose
    )

    client.unload_model("simple")
    if client.is_model_ready("simple"):
        print("FAILED: model still ready after unload")
        sys.exit(1)

    inputs = [grpcclient.InferInput("INPUT0", [1, 16], "INT32"),
              grpcclient.InferInput("INPUT1", [1, 16], "INT32")]
    data = np.zeros((1, 16), dtype=np.int32)
    inputs[0].set_data_from_numpy(data)
    inputs[1].set_data_from_numpy(data)
    try:
        client.infer("simple", inputs)
        print("FAILED: infer succeeded on unloaded model")
        sys.exit(1)
    except InferenceServerException:
        pass

    client.load_model("simple")
    if not client.is_model_ready("simple"):
        print("FAILED: model not ready after load")
        sys.exit(1)
    client.infer("simple", inputs)
    client.close()
    print("PASS: model control")


if __name__ == "__main__":
    main()
