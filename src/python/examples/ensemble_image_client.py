#!/usr/bin/env python3
"""Drive the `image_ensemble` model (preprocess -> ResNet-50 ensemble
scheduling): raw uint8 pixels in, top-k classes out (role of reference
src/python/examples/ensemble_image_client.py)."""

import argparse
import sys

import numpy as np


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-v", "--verbose", action="store_true")
    parser.add_argument("-u", "--url", default="localhost:8000")
    parser.add_argument("-i", "--protocol", default="HTTP",
                        choices=["HTTP", "GRPC", "http", "grpc"])
    parser.add_argument("-c", "--classes", type=int, default=3)
    args = parser.parse_args()

    protocol = args.protocol.lower()
    if protocol == "grpc":
        import tritonclient.grpc as tclient
    else:
        import tritonclient.http as tclient

    client = tclient.InferenceServerClient(
        url=args.url, verbose=args.verbose)

    rng = np.random.RandomState(3)
    raw = rng.randint(0, 255, (1, 224, 224, 3)).astype(np.uint8)
    inp = tclient.InferInput("RAW_IMAGE", [1, 224, 224, 3], "UINT8")
    inp.set_data_from_numpy(raw)
    if protocol == "grpc":
        outputs = [tclient.InferRequestedOutput(
            "OUTPUT", class_count=args.classes)]
    else:
        outputs = [tclient.InferRequestedOutput(
            "OUTPUT", binary_data=True, class_count=args.classes)]

    result = client.infer("image_ensemble", [inp], outputs=outputs)
    classes = result.as_numpy("OUTPUT").reshape(-1)
    if len(classes) != args.classes:
        print("FAILED: expected {} classes, got {}".format(
            args.classes, len(classes)))
        sys.exit(1)
    for entry in classes:
        value, index, label = entry.decode("utf-8").split(":")
        print("    {} ({}) = {}".format(index, label, value))
    client.close()
    print("PASS: ensemble image client")


if __name__ == "__main__":
    main()
