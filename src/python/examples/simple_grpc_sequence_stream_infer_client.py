#!/usr/bin/env python3
"""Stateful sequences over the bidirectional gRPC stream: two sequences
issued on one stream, responses correlated by request id (role of
reference simple_grpc_sequence_stream_infer_client.py)."""

import argparse
import queue
import sys

import numpy as np

import tritonclient.grpc as grpcclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-v", "--verbose", action="store_true")
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    client = grpcclient.InferenceServerClient(
        url=args.url, verbose=args.verbose
    )
    results = queue.Queue()
    client.start_stream(
        callback=lambda result, error: results.put((result, error))
    )

    values = [11, 7, 5, 3, 2, 0, 1]
    seq0, seq1 = 3007, 3008
    n_sent = 0
    try:
        for i, v in enumerate(values):
            start = i == 0
            end = i == len(values) - 1
            for seq, val in ((seq0, v), (seq1, -v)):
                inp = grpcclient.InferInput("INPUT", [1], "INT32")
                inp.set_data_from_numpy(np.array([val], dtype=np.int32))
                client.async_stream_infer(
                    "sequence_accumulate", [inp],
                    request_id="{}_{}".format(seq, i),
                    sequence_id=seq, sequence_start=start, sequence_end=end,
                )
                n_sent += 1
        acc = {}
        for _ in range(n_sent):
            result, error = results.get(timeout=30)
            if error is not None:
                print("stream error: " + str(error))
                sys.exit(1)
            rid = result.get_response().id
            acc[rid] = int(result.as_numpy("OUTPUT")[0])
    finally:
        client.stop_stream()

    last = len(values) - 1
    expected = sum(values)
    final0 = acc["{}_{}".format(seq0, last)]
    final1 = acc["{}_{}".format(seq1, last)]
    print("sequence {}: {}".format(seq0, final0))
    print("sequence {}: {}".format(seq1, final1))
    if final0 != expected or final1 != -expected:
        print("FAILED: wrong accumulated values")
        sys.exit(1)
    client.close()
    print("PASS: sequence stream")


if __name__ == "__main__":
    main()
