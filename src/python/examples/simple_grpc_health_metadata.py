#!/usr/bin/env python3
"""Health, metadata, config, repository-index and statistics queries over
gRPC (role of reference src/python/examples/simple_grpc_health_metadata.py)."""

import argparse
import sys

import tritonclient.grpc as grpcclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-v", "--verbose", action="store_true")
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    client = grpcclient.InferenceServerClient(
        url=args.url, verbose=args.verbose
    )

    if not client.is_server_live():
        print("FAILED: server not live")
        sys.exit(1)
    if not client.is_server_ready():
        print("FAILED: server not ready")
        sys.exit(1)
    if not client.is_model_ready("simple"):
        print("FAILED: model 'simple' not ready")
        sys.exit(1)

    server_metadata = client.get_server_metadata()
    print("server: {} {}".format(
        server_metadata.name, server_metadata.version))

    model_metadata = client.get_model_metadata("simple")
    if model_metadata.name != "simple":
        print("FAILED: wrong model metadata name")
        sys.exit(1)
    print("model inputs: {}".format(
        [t.name for t in model_metadata.inputs]))

    model_config = client.get_model_config("simple")
    if model_config.config.name != "simple":
        print("FAILED: wrong model config name")
        sys.exit(1)

    index = client.get_model_repository_index()
    if not any(m.name == "simple" for m in index.models):
        print("FAILED: 'simple' not in repository index")
        sys.exit(1)

    stats = client.get_inference_statistics("simple")
    if not stats.model_stats:
        print("FAILED: no statistics for 'simple'")
        sys.exit(1)
    client.close()
    print("PASS: health and metadata")


if __name__ == "__main__":
    main()
