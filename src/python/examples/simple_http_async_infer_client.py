#!/usr/bin/env python3
"""Async (worker-pool) infer over HTTP; fires several requests and
collects the futures (role of reference
src/python/examples/simple_http_async_infer_client.py)."""

import argparse
import sys

import numpy as np

import tritonclient.http as httpclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-v", "--verbose", action="store_true")
    parser.add_argument("-u", "--url", default="localhost:8000")
    args = parser.parse_args()

    client = httpclient.InferenceServerClient(
        url=args.url, verbose=args.verbose, concurrency=4
    )

    input0_data = np.arange(16, dtype=np.int32).reshape(1, 16)
    input1_data = np.full((1, 16), 2, dtype=np.int32)
    inputs = [
        httpclient.InferInput("INPUT0", [1, 16], "INT32"),
        httpclient.InferInput("INPUT1", [1, 16], "INT32"),
    ]
    inputs[0].set_data_from_numpy(input0_data)
    inputs[1].set_data_from_numpy(input1_data)

    async_requests = [
        client.async_infer("simple", inputs) for _ in range(8)
    ]
    for request in async_requests:
        result = request.get_result()
        if not np.array_equal(
            result.as_numpy("OUTPUT0"), input0_data + input1_data
        ):
            print("error: incorrect sum")
            sys.exit(1)
    client.close()
    print("PASS: async infer")


if __name__ == "__main__":
    main()
