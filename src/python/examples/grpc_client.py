#!/usr/bin/env python3
"""Minimal infer using the raw generated gRPC stubs and nothing else —
the "bring your own language" recipe (role of reference
src/python/examples/grpc_client.py)."""

import argparse
import sys

import grpc
import numpy as np

from tritonclient.grpc import grpc_service_pb2 as pb
from tritonclient.grpc._service import METHODS, SERVICE


def call(channel, name, request):
    req_cls, resp_cls, _ = METHODS[name]
    return channel.unary_unary(
        "/{}/{}".format(SERVICE, name),
        request_serializer=req_cls.SerializeToString,
        response_deserializer=resp_cls.FromString,
    )(request)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    channel = grpc.insecure_channel(args.url)

    metadata = call(
        channel, "ServerMetadata", pb.ServerMetadataRequest())
    print("server: {} {}".format(metadata.name, metadata.version))

    input0 = np.arange(16, dtype=np.int32).reshape(1, 16)
    input1 = np.full((1, 16), 1, dtype=np.int32)
    request = pb.ModelInferRequest(model_name="simple")
    for name, arr in (("INPUT0", input0), ("INPUT1", input1)):
        tensor = request.inputs.add()
        tensor.name = name
        tensor.datatype = "INT32"
        tensor.shape.extend(arr.shape)
        request.raw_input_contents.append(arr.tobytes())

    response = call(channel, "ModelInfer", request)
    output0 = np.frombuffer(
        response.raw_output_contents[0], dtype=np.int32).reshape(1, 16)
    output1 = np.frombuffer(
        response.raw_output_contents[1], dtype=np.int32).reshape(1, 16)
    if not np.array_equal(output0, input0 + input1):
        print("FAILED: incorrect sum")
        sys.exit(1)
    if not np.array_equal(output1, input0 - input1):
        print("FAILED: incorrect difference")
        sys.exit(1)
    channel.close()
    print("PASS: raw grpc client")


if __name__ == "__main__":
    main()
