#!/usr/bin/env python3
"""Infer passing INT32 input via the typed ``contents.int_contents``
field instead of raw bytes (role of reference
grpc_explicit_int_content_client.py)."""

import argparse
import sys

import grpc
import numpy as np

from tritonclient.grpc import grpc_service_pb2 as pb
from tritonclient.grpc._service import METHODS, SERVICE


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    channel = grpc.insecure_channel(args.url)
    req_cls, resp_cls, _ = METHODS["ModelInfer"]
    infer = channel.unary_unary(
        "/{}/ModelInfer".format(SERVICE),
        request_serializer=req_cls.SerializeToString,
        response_deserializer=resp_cls.FromString,
    )

    input0 = np.arange(16, dtype=np.int32).reshape(1, 16)
    input1 = np.full((1, 16), 1, dtype=np.int32)
    request = pb.ModelInferRequest(model_name="simple")
    for name, arr in (("INPUT0", input0), ("INPUT1", input1)):
        tensor = request.inputs.add()
        tensor.name = name
        tensor.datatype = "INT32"
        tensor.shape.extend(arr.shape)
        tensor.contents.int_contents.extend(int(x) for x in arr.flat)

    response = infer(request)
    output0 = np.frombuffer(
        response.raw_output_contents[0], dtype=np.int32).reshape(1, 16)
    if not np.array_equal(output0, input0 + input1):
        print("FAILED: incorrect sum")
        sys.exit(1)
    channel.close()
    print("PASS: explicit int contents")


if __name__ == "__main__":
    main()
