#!/usr/bin/env python3
"""Infer over a channel configured with explicit keepalive options (role
of reference simple_grpc_keepalive_client.py; reference KeepAliveOptions
grpc_client.h:61-82)."""

import argparse
import sys

import numpy as np

import tritonclient.grpc as grpcclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-v", "--verbose", action="store_true")
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    keepalive_options = grpcclient.KeepAliveOptions(
        keepalive_time_ms=1000,
        keepalive_timeout_ms=500,
        keepalive_permit_without_calls=True,
        http2_max_pings_without_data=0,
    )
    client = grpcclient.InferenceServerClient(
        url=args.url, verbose=args.verbose,
        keepalive_options=keepalive_options,
    )

    input0_data = np.arange(16, dtype=np.int32).reshape(1, 16)
    input1_data = np.full((1, 16), 3, dtype=np.int32)
    inputs = [
        grpcclient.InferInput("INPUT0", [1, 16], "INT32"),
        grpcclient.InferInput("INPUT1", [1, 16], "INT32"),
    ]
    inputs[0].set_data_from_numpy(input0_data)
    inputs[1].set_data_from_numpy(input1_data)

    result = client.infer("simple", inputs)
    if not np.array_equal(
        result.as_numpy("OUTPUT0"), input0_data + input1_data
    ):
        print("FAILED: incorrect sum")
        sys.exit(1)
    client.close()
    print("PASS: keepalive")


if __name__ == "__main__":
    main()
