#!/usr/bin/env python3
"""Fleet-supervisor demo: a plain ``tritonclient.http`` client pointed
at a supervised fleet's router keeps working while a replica server
PROCESS is SIGKILLed — the supervisor respawns it, the router's live
membership follows, and the client never sees an error.

The demo (1) spawns two real replica processes under a
``tpuserver.fleet.FleetSupervisor`` (each ``tools/fleet.py
--serve-replica`` with its own port), (2) runs unary traffic through
the router, (3) SIGKILLs one replica — no drain, no warning — and
keeps the traffic flowing off the surviving replica, and (4) waits for
the supervisor to heal the fleet back to two members before a final
round of traffic.

Self-contained: the fleet is spun up by the demo itself (a healing
demo needs a replica it is allowed to kill).  ``-u`` is accepted for
harness compatibility and ignored.  In production run the fleet as its
own process tree: ``python tools/fleet.py --replicas 2 ...``.
"""

import argparse
import os
import signal
import sys
import time

import numpy as np

import tritonclient.http as httpclient

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-v", "--verbose", action="store_true")
    parser.add_argument("-u", "--url", default=None,
                        help="ignored: this demo kills its own "
                             "supervised replica processes")
    parser.add_argument("-n", "--requests", type=int, default=8)
    args = parser.parse_args()

    from tpuserver.fleet import FleetSupervisor

    command = [
        sys.executable, os.path.join(REPO, "tools", "fleet.py"),
        "--serve-replica", "--port", "{port}", "--scope", "{scope}",
        "--models", "simple",
    ]
    supervisor = FleetSupervisor(
        command, replicas=2, min_replicas=2, max_replicas=2,
        probe_interval_s=0.15, probe_timeout_s=5.0, unhealthy_after=20,
        start_timeout_s=120.0, drain_grace_s=5.0,
        restart_backoff_s=0.05, scope_prefix="demo-fleet-r",
        router_kwargs={"probe_interval_s": 0.1},
        env={"PYTHONPATH": os.path.join(REPO, "src", "python"),
             "JAX_PLATFORMS": "cpu"},
        verbose=args.verbose,
    ).start()
    failures = []
    try:
        if not supervisor.wait_ready(timeout_s=120):
            raise SystemExit("fleet never became ready")
        print("router:   {}".format(supervisor.router.url))
        for rep in supervisor.stats()["replicas"]:
            print("replica:  {url} [{scope}] pid={pid}".format(**rep))

        client = httpclient.InferenceServerClient(supervisor.router.url)
        data = np.arange(16, dtype=np.int32)
        inputs = [httpclient.InferInput("INPUT0", [16], "INT32"),
                  httpclient.InferInput("INPUT1", [16], "INT32")]
        inputs[0].set_data_from_numpy(data)
        inputs[1].set_data_from_numpy(np.ones(16, dtype=np.int32))

        def traffic(label):
            ok = 0
            for i in range(args.requests):
                try:
                    result = client.infer("simple", inputs)
                    if np.array_equal(result.as_numpy("OUTPUT0"),
                                      data + 1):
                        ok += 1
                    else:
                        failures.append(
                            "{}: wrong result at {}".format(label, i))
                except Exception as e:  # noqa: BLE001 — counted
                    failures.append("{}: request {} failed: {}".format(
                        label, i, e))
            print("{}: {}/{} requests ok".format(
                label, ok, args.requests))

        traffic("healthy fleet")

        victim = supervisor.stats()["replicas"][0]
        print("--- SIGKILL replica {} (pid {}) ---".format(
            victim["url"], victim["pid"]))
        os.kill(victim["pid"], signal.SIGKILL)
        time.sleep(0.3)  # let routing notice; the survivor carries on
        traffic("one replica dead")

        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            stats = supervisor.stats()
            if stats["replica_restarts"] >= 1 and stats["up"] == 2:
                break
            time.sleep(0.1)
        stats = supervisor.stats()
        print("healed: restarts={} up={} retired={}".format(
            stats["replica_restarts"], stats["up"],
            stats["retired_replicas"]))
        if stats["replica_restarts"] < 1 or stats["up"] != 2:
            failures.append("supervisor never healed the fleet: "
                            "{}".format(stats))
        replaced = next(r for r in stats["replicas"]
                        if r["index"] == victim["index"])
        if replaced["pid"] == victim["pid"]:
            failures.append("replica was not actually respawned")

        traffic("healed fleet")
        client.close()
    finally:
        supervisor.stop()

    if failures:
        for failure in failures:
            print("FAIL: {}".format(failure))
        sys.exit(1)
    print("PASS: a SIGKILL'd replica process was respawned and the "
          "fleet healed with zero client-visible errors")


if __name__ == "__main__":
    main()
