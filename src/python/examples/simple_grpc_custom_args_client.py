#!/usr/bin/env python3
"""Infer with custom request headers and request parameters attached
(role of reference simple_grpc_custom_args_client.py)."""

import argparse
import sys

import numpy as np

import tritonclient.grpc as grpcclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-v", "--verbose", action="store_true")
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    client = grpcclient.InferenceServerClient(
        url=args.url, verbose=args.verbose
    )

    input0_data = np.arange(16, dtype=np.int32).reshape(1, 16)
    input1_data = np.full((1, 16), 4, dtype=np.int32)
    inputs = [
        grpcclient.InferInput("INPUT0", [1, 16], "INT32"),
        grpcclient.InferInput("INPUT1", [1, 16], "INT32"),
    ]
    inputs[0].set_data_from_numpy(input0_data)
    inputs[1].set_data_from_numpy(input1_data)

    result = client.infer(
        "simple", inputs,
        headers={"x-client-example": "custom-args"},
        parameters={"example_param": "value", "example_flag": True},
        request_id="custom-args-1",
        priority=1,
    )
    if result.get_response().id != "custom-args-1":
        print("FAILED: request id not echoed")
        sys.exit(1)
    if not np.array_equal(
        result.as_numpy("OUTPUT0"), input0_data + input1_data
    ):
        print("FAILED: incorrect sum")
        sys.exit(1)
    client.close()
    print("PASS: custom args")


if __name__ == "__main__":
    main()
