#!/usr/bin/env python3
"""BYTES tensors through system shared memory over HTTP: string inputs
are length-prefix serialized into the region (role of reference
simple_http_shm_string_client.py)."""

import argparse
import sys

import numpy as np

import tritonclient.http as httpclient
from tritonclient.utils import serialized_byte_size
from tritonclient.utils import shared_memory as shm


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-v", "--verbose", action="store_true")
    parser.add_argument("-u", "--url", default="localhost:8000")
    args = parser.parse_args()

    client = httpclient.InferenceServerClient(
        url=args.url, verbose=args.verbose
    )

    in0 = np.arange(16, dtype=np.int32)
    in1 = np.full(16, 1, dtype=np.int32)
    input0_str = np.array(
        [str(x).encode("utf-8") for x in in0], dtype=np.object_
    ).reshape(1, 16)
    input1_str = np.array(
        [str(x).encode("utf-8") for x in in1], dtype=np.object_
    ).reshape(1, 16)
    size0 = serialized_byte_size(input0_str)
    size1 = serialized_byte_size(input1_str)

    shm_ip_handle = shm.create_shared_memory_region(
        "str_input_data", "/str_input_http", size0 + size1
    )
    try:
        shm.set_shared_memory_region(
            shm_ip_handle, [input0_str, input1_str]
        )
        client.register_system_shared_memory(
            "str_input_data", "/str_input_http", size0 + size1
        )

        inputs = [
            httpclient.InferInput("INPUT0", [1, 16], "BYTES"),
            httpclient.InferInput("INPUT1", [1, 16], "BYTES"),
        ]
        inputs[0].set_shared_memory("str_input_data", size0)
        inputs[1].set_shared_memory("str_input_data", size1, offset=size0)

        result = client.infer("simple_string", inputs)
        output0 = result.as_numpy("OUTPUT0").reshape(16)
        output1 = result.as_numpy("OUTPUT1").reshape(16)
        for i in range(16):
            if int(output0[i]) != in0[i] + in1[i]:
                print("FAILED: incorrect sum")
                sys.exit(1)
            if int(output1[i]) != in0[i] - in1[i]:
                print("FAILED: incorrect difference")
                sys.exit(1)
    finally:
        client.unregister_system_shared_memory("str_input_data")
        shm.destroy_shared_memory_region(shm_ip_handle)
    client.close()
    print("PASS: string shared memory")


if __name__ == "__main__":
    main()
