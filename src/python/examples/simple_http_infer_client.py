#!/usr/bin/env python3
"""Sync infer on the `simple` add/sub model over HTTP (role of reference
src/python/examples/simple_http_infer_client.py)."""

import argparse
import sys

import numpy as np

import tritonclient.http as httpclient
from tritonclient.utils import InferenceServerException


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-v", "--verbose", action="store_true")
    parser.add_argument("-u", "--url", default="localhost:8000")
    args = parser.parse_args()

    try:
        client = httpclient.InferenceServerClient(
            url=args.url, verbose=args.verbose
        )
    except Exception as e:
        print("client creation failed: " + str(e))
        sys.exit(1)

    input0_data = np.arange(16, dtype=np.int32).reshape(1, 16)
    input1_data = np.full((1, 16), 1, dtype=np.int32)

    inputs = [
        httpclient.InferInput("INPUT0", [1, 16], "INT32"),
        httpclient.InferInput("INPUT1", [1, 16], "INT32"),
    ]
    inputs[0].set_data_from_numpy(input0_data, binary_data=True)
    inputs[1].set_data_from_numpy(input1_data, binary_data=False)

    outputs = [
        httpclient.InferRequestedOutput("OUTPUT0", binary_data=True),
        httpclient.InferRequestedOutput("OUTPUT1", binary_data=False),
    ]

    try:
        result = client.infer("simple", inputs, outputs=outputs)
    except InferenceServerException as e:
        print("inference failed: " + str(e))
        sys.exit(1)

    output0_data = result.as_numpy("OUTPUT0")
    output1_data = result.as_numpy("OUTPUT1")
    for i in range(16):
        print(
            "{} + {} = {}".format(
                input0_data[0][i], input1_data[0][i], output0_data[0][i]
            )
        )
        if (input0_data[0][i] + input1_data[0][i]) != output0_data[0][i]:
            print("error: incorrect sum")
            sys.exit(1)
        if (input0_data[0][i] - input1_data[0][i]) != output1_data[0][i]:
            print("error: incorrect difference")
            sys.exit(1)

    stat = client.get_inference_stat()
    if stat.completed_request_count < 1:
        print("error: client statistics not recorded")
        sys.exit(1)
    client.close()
    print("PASS: infer")


if __name__ == "__main__":
    main()
