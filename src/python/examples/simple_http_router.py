#!/usr/bin/env python3
"""Fleet-router demo: a PLAIN ``tritonclient.http`` client pointed at a
``tpuserver.router.FleetRouter`` over two in-process replicas gets
health-aware routing, drain rotation, and cross-replica stream handoff
for free — no EndpointPool, no client-side smarts.

The demo (1) streams a generation through the router while an injected
fault severs the serving replica's connection mid-stream: the router
re-admits prompt + emitted history on the other replica and the client
sees one continuous token-identical stream; (2) drains one replica
mid-traffic: unary requests keep succeeding because the prober rotates
it out before anything lands there.

Self-contained: the replicas and the router are spun up in-process
(a handoff demo needs a replica it is allowed to kill), so no external
server is required.  ``-u`` is accepted for harness compatibility and
ignored.  In production run the router as its own process:
``python tools/router.py --backends a:8000,b:8000``.
"""

import argparse
import sys
import time

import numpy as np

import tritonclient.http as httpclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-v", "--verbose", action="store_true")
    parser.add_argument("-u", "--url", default=None,
                        help="ignored: this demo severs streams on its "
                             "own in-process replicas")
    parser.add_argument("-t", "--max-tokens", type=int, default=8)
    args = parser.parse_args()

    from tpuserver import faults
    from tpuserver.core import InferenceServer
    from tpuserver.http_frontend import HttpFrontend
    from tpuserver.models import llama
    from tpuserver.models.llama_serving import LlamaGenerateModel
    from tpuserver.models.simple import SimpleModel
    from tpuserver.router import FleetRouter

    cfg = llama.tiny(vocab=256)
    scopes = ("demo-a", "demo-b")
    cores = [
        InferenceServer(
            [LlamaGenerateModel(cfg=cfg, max_seq=64, max_slots=2,
                                restart_backoff_s=0.01),
             SimpleModel()],
            fault_scope=scope)
        for scope in scopes
    ]
    frontends = [HttpFrontend(core, port=0).start() for core in cores]
    urls = ["127.0.0.1:{}".format(f.port) for f in frontends]
    router = FleetRouter(urls, probe_interval_s=0.1).start()
    print("replicas: {}".format(urls))
    print("router:   {}".format(router.url))

    prompt = np.array([3, 1, 4, 1, 5], dtype=np.int32)
    budget = np.array([args.max_tokens], dtype=np.int32)
    client = httpclient.InferenceServerClient(router.url,
                                              verbose=args.verbose)
    failures = []

    def stream_tokens():
        return [
            int(out["data"][0])
            for event in client.generate_stream(
                "llama_generate",
                {"PROMPT_IDS": prompt, "MAX_TOKENS": budget})
            for out in event.get("outputs", [])
            if out["name"] == "TOKEN"
        ]

    # fault-free reference: greedy decode is deterministic and the
    # replicas share weights, so every later stream must match this
    reference = stream_tokens()
    print("reference tokens: {}".format(reference))

    print("--- severing the serving replica's connection mid-stream ---")
    for scope in scopes:  # whichever replica is home drops the stream
        faults.install("http.generate_stream", mode="raise", times=1,
                       skip=3, scope=scope)
    tokens = stream_tokens()
    faults.clear()
    stats = router.stats()
    print("tokens through the kill: {}".format(tokens))
    print("router absorbed it: handoffs={} failovers={}".format(
        stats["handoffs"], stats["failovers"]))
    if tokens != reference:
        failures.append("handoff stream diverged: {} != {}".format(
            tokens, reference))
    if stats["handoffs"] < 1:
        failures.append("no cross-replica handoff recorded")

    print("--- draining replica A mid-traffic ---")
    cores[0].begin_drain()
    deadline = time.monotonic() + 5.0
    while (any(r["eligible"] and r["url"] == urls[0]
               for r in router.stats()["replicas"])
           and time.monotonic() < deadline):
        time.sleep(0.05)
    data = np.arange(16, dtype=np.int32)
    inputs = [httpclient.InferInput("INPUT0", [16], "INT32"),
              httpclient.InferInput("INPUT1", [16], "INT32")]
    inputs[0].set_data_from_numpy(data)
    inputs[1].set_data_from_numpy(np.ones(16, dtype=np.int32))
    for i in range(6):
        try:
            result = client.infer("simple", inputs)
            if not np.array_equal(result.as_numpy("OUTPUT0"), data + 1):
                failures.append("wrong unary result at {}".format(i))
        except Exception as e:  # noqa: BLE001 — counted as a failure
            failures.append("unary request {} failed during drain: "
                            "{}".format(i, e))
    cores[0].mark_ready()
    for rep in router.stats()["replicas"]:
        print("replica {url}: eligible={eligible} requests={requests} "
              "failures={failures}".format(**rep))

    client.close()
    router.stop()
    for f in frontends:
        f.stop()
    for c in cores:
        c.close()

    if failures:
        for failure in failures:
            print("FAIL: {}".format(failure))
        sys.exit(1)
    print("PASS: replica death and drain were invisible to a plain "
          "client behind the router")


if __name__ == "__main__":
    main()
