#!/usr/bin/env python3
"""Decoupled streaming: one request to the `repeat_int32` model produces
N streamed responses plus the empty final-response marker (role of
reference simple_grpc_custom_repeat.py:78-105)."""

import argparse
import queue
import sys

import numpy as np

import tritonclient.grpc as grpcclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-v", "--verbose", action="store_true")
    parser.add_argument("-u", "--url", default="localhost:8001")
    parser.add_argument("-r", "--repeat-count", type=int, default=6)
    args = parser.parse_args()

    client = grpcclient.InferenceServerClient(
        url=args.url, verbose=args.verbose
    )
    results = queue.Queue()
    client.start_stream(
        callback=lambda result, error: results.put((result, error))
    )

    values = np.arange(args.repeat_count, dtype=np.int32) * 10
    inputs = [
        grpcclient.InferInput("IN", [len(values)], "INT32"),
        grpcclient.InferInput("DELAY", [len(values)], "UINT32"),
        grpcclient.InferInput("WAIT", [1], "UINT32"),
    ]
    inputs[0].set_data_from_numpy(values)
    inputs[1].set_data_from_numpy(
        np.full(len(values), 1000, dtype=np.uint32))
    inputs[2].set_data_from_numpy(np.array([500], dtype=np.uint32))

    try:
        client.async_stream_infer(
            "repeat_int32", inputs, enable_empty_final_response=True
        )
        received = []
        while True:
            result, error = results.get(timeout=30)
            if error is not None:
                print("stream error: " + str(error))
                sys.exit(1)
            response = result.get_response()
            final = response.parameters.get("triton_final_response")
            if final is not None and final.bool_param:
                break
            received.append(int(result.as_numpy("OUT")[0]))
    finally:
        client.stop_stream()

    print("received: {}".format(received))
    if received != list(values):
        print("FAILED: wrong streamed values")
        sys.exit(1)
    client.close()
    print("PASS: custom repeat")


if __name__ == "__main__":
    main()
