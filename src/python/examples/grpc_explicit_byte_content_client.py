#!/usr/bin/env python3
"""Infer passing BYTES input via the typed ``contents.bytes_contents``
field (role of reference grpc_explicit_byte_content_client.py)."""

import argparse
import sys

import grpc
import numpy as np

from tritonclient.grpc import grpc_service_pb2 as pb
from tritonclient.grpc._service import METHODS, SERVICE


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    channel = grpc.insecure_channel(args.url)
    req_cls, resp_cls, _ = METHODS["ModelInfer"]
    infer = channel.unary_unary(
        "/{}/ModelInfer".format(SERVICE),
        request_serializer=req_cls.SerializeToString,
        response_deserializer=resp_cls.FromString,
    )

    in0 = np.arange(16, dtype=np.int32)
    in1 = np.full(16, 1, dtype=np.int32)
    request = pb.ModelInferRequest(model_name="simple_string")
    for name, arr in (("INPUT0", in0), ("INPUT1", in1)):
        tensor = request.inputs.add()
        tensor.name = name
        tensor.datatype = "BYTES"
        tensor.shape.extend([1, 16])
        tensor.contents.bytes_contents.extend(
            str(x).encode("utf-8") for x in arr
        )

    response = infer(request)
    # outputs come back length-prefix serialized in raw_output_contents
    import struct

    raw = response.raw_output_contents[0]
    values = []
    pos = 0
    while pos < len(raw):
        (length,) = struct.unpack_from("<I", raw, pos)
        pos += 4
        values.append(int(raw[pos : pos + length]))
        pos += length
    if values != [int(a + b) for a, b in zip(in0, in1)]:
        print("FAILED: incorrect sums")
        sys.exit(1)
    channel.close()
    print("PASS: explicit byte contents")


if __name__ == "__main__":
    main()
