#!/usr/bin/env python3
"""Reuse InferInput / InferRequestedOutput objects across many requests
and both issue modes — the allocation-free steady-state pattern (role of
reference src/python/examples/reuse_infer_objects_client.py)."""

import argparse
import sys

import numpy as np


def check(result, input0_data, input1_data):
    if not np.array_equal(
        result.as_numpy("OUTPUT0"), input0_data + input1_data
    ):
        print("FAILED: incorrect sum")
        sys.exit(1)
    if not np.array_equal(
        result.as_numpy("OUTPUT1"), input0_data - input1_data
    ):
        print("FAILED: incorrect difference")
        sys.exit(1)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-v", "--verbose", action="store_true")
    parser.add_argument("-u", "--url", default="localhost:8000")
    parser.add_argument("-i", "--protocol", default="HTTP",
                        choices=["HTTP", "GRPC", "http", "grpc"])
    args = parser.parse_args()

    protocol = args.protocol.lower()
    if protocol == "grpc":
        import tritonclient.grpc as tclient
    else:
        import tritonclient.http as tclient
    client = tclient.InferenceServerClient(
        url=args.url, verbose=args.verbose)

    input0_data = np.arange(16, dtype=np.int32).reshape(1, 16)
    input1_data = np.full((1, 16), 1, dtype=np.int32)
    inputs = [
        tclient.InferInput("INPUT0", [1, 16], "INT32"),
        tclient.InferInput("INPUT1", [1, 16], "INT32"),
    ]
    outputs = [
        tclient.InferRequestedOutput("OUTPUT0"),
        tclient.InferRequestedOutput("OUTPUT1"),
    ]

    # The same input/output objects are reused across iterations; only the
    # tensor contents change.
    for it in range(4):
        input0_data = input0_data + it
        inputs[0].set_data_from_numpy(input0_data)
        inputs[1].set_data_from_numpy(input1_data)
        result = client.infer("simple", inputs, outputs=outputs)
        check(result, input0_data, input1_data)

    # Same objects through the async path.
    inputs[0].set_data_from_numpy(input0_data)
    if protocol == "grpc":
        import queue

        done = queue.Queue()
        for _ in range(3):
            client.async_infer(
                "simple", inputs,
                callback=lambda result, error: done.put((result, error)),
                outputs=outputs,
            )
        for _ in range(3):
            result, error = done.get(timeout=30)
            if error is not None:
                print("async infer failed: " + str(error))
                sys.exit(1)
            check(result, input0_data, input1_data)
    else:
        futures = [
            client.async_infer("simple", inputs, outputs=outputs)
            for _ in range(3)
        ]
        for fut in futures:
            check(fut.get_result(), input0_data, input1_data)

    client.close()
    print("PASS: reuse infer objects")


if __name__ == "__main__":
    main()
