#!/usr/bin/env python3
"""System shared-memory data plane over gRPC (role of reference
simple_grpc_shm_client.py)."""

import argparse
import sys

import numpy as np

import tritonclient.grpc as grpcclient
from tritonclient.utils import shared_memory as shm


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-v", "--verbose", action="store_true")
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    client = grpcclient.InferenceServerClient(
        url=args.url, verbose=args.verbose
    )
    client.unregister_system_shared_memory()

    input0_data = np.arange(16, dtype=np.int32).reshape(1, 16)
    input1_data = np.full((1, 16), 1, dtype=np.int32)
    byte_size = input0_data.nbytes

    shm_ip_handle = shm.create_shared_memory_region(
        "input_data", "/input_simple_grpc", byte_size * 2
    )
    shm_op_handle = shm.create_shared_memory_region(
        "output_data", "/output_simple_grpc", byte_size * 2
    )
    try:
        shm.set_shared_memory_region(
            shm_ip_handle, [input0_data, input1_data]
        )
        client.register_system_shared_memory(
            "input_data", "/input_simple_grpc", byte_size * 2
        )
        client.register_system_shared_memory(
            "output_data", "/output_simple_grpc", byte_size * 2
        )

        inputs = [
            grpcclient.InferInput("INPUT0", [1, 16], "INT32"),
            grpcclient.InferInput("INPUT1", [1, 16], "INT32"),
        ]
        inputs[0].set_shared_memory("input_data", byte_size)
        inputs[1].set_shared_memory("input_data", byte_size,
                                    offset=byte_size)
        outputs = [
            grpcclient.InferRequestedOutput("OUTPUT0"),
            grpcclient.InferRequestedOutput("OUTPUT1"),
        ]
        outputs[0].set_shared_memory("output_data", byte_size)
        outputs[1].set_shared_memory("output_data", byte_size,
                                     offset=byte_size)

        client.infer("simple", inputs, outputs=outputs)

        sum_data = shm.get_contents_as_numpy(
            shm_op_handle, np.int32, [1, 16]
        )
        diff_data = shm.get_contents_as_numpy(
            shm_op_handle, np.int32, [1, 16], offset=byte_size
        )
        if not np.array_equal(sum_data, input0_data + input1_data):
            print("FAILED: incorrect sum in shm")
            sys.exit(1)
        if not np.array_equal(diff_data, input0_data - input1_data):
            print("FAILED: incorrect difference in shm")
            sys.exit(1)
        status = client.get_system_shared_memory_status()
        if len(status.regions) < 2:
            print("FAILED: shm status missing regions")
            sys.exit(1)
    finally:
        client.unregister_system_shared_memory()
        shm.destroy_shared_memory_region(shm_ip_handle)
        shm.destroy_shared_memory_region(shm_op_handle)
    client.close()
    print("PASS: system shared memory")


if __name__ == "__main__":
    main()
