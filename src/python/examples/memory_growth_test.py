#!/usr/bin/env python3
"""Client memory-growth check: loop inference and verify the process RSS
stays bounded (role of reference src/python/examples/memory_growth_test.py
/ C++ memory_leak_test.cc)."""

import argparse
import resource
import sys

import numpy as np


def rss_mb():
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-v", "--verbose", action="store_true")
    parser.add_argument("-u", "--url", default="localhost:8000")
    parser.add_argument("-i", "--protocol", default="HTTP",
                        choices=["HTTP", "GRPC", "http", "grpc"])
    parser.add_argument("-n", "--iterations", type=int, default=500)
    parser.add_argument("--max-growth-mb", type=float, default=32.0)
    args = parser.parse_args()

    protocol = args.protocol.lower()
    if protocol == "grpc":
        import tritonclient.grpc as tclient
        url = args.url
    else:
        import tritonclient.http as tclient
        url = args.url
    client = tclient.InferenceServerClient(url=url, verbose=args.verbose)

    input0_data = np.arange(16, dtype=np.int32).reshape(1, 16)
    input1_data = np.full((1, 16), 1, dtype=np.int32)
    inputs = [
        tclient.InferInput("INPUT0", [1, 16], "INT32"),
        tclient.InferInput("INPUT1", [1, 16], "INT32"),
    ]
    inputs[0].set_data_from_numpy(input0_data)
    inputs[1].set_data_from_numpy(input1_data)

    # warmup establishes steady-state allocations (pools, buffers)
    for _ in range(50):
        client.infer("simple", inputs)
    baseline = rss_mb()

    for i in range(args.iterations):
        result = client.infer("simple", inputs)
        if i == 0 and not np.array_equal(
            result.as_numpy("OUTPUT0"), input0_data + input1_data
        ):
            print("FAILED: incorrect result")
            sys.exit(1)

    growth = rss_mb() - baseline
    print("rss baseline {:.1f} MB, growth after {} iterations: "
          "{:.1f} MB".format(baseline, args.iterations, growth))
    if growth > args.max_growth_mb:
        print("FAILED: memory growth {:.1f} MB exceeds {} MB".format(
            growth, args.max_growth_mb))
        sys.exit(1)
    client.close()
    print("PASS: memory growth")


if __name__ == "__main__":
    main()
