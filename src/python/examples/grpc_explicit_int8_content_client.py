#!/usr/bin/env python3
"""Infer passing INT8-shaped data via the typed contents field; INT8
rides ``int_contents`` per the KServe v2 proto (role of reference
grpc_explicit_int8_content_client.py).  Uses the identity model since
the fixture `simple` is INT32."""

import argparse
import sys

import grpc
import numpy as np

from tritonclient.grpc import grpc_service_pb2 as pb
from tritonclient.grpc._service import METHODS, SERVICE


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    channel = grpc.insecure_channel(args.url)
    req_cls, resp_cls, _ = METHODS["ModelInfer"]
    infer = channel.unary_unary(
        "/{}/ModelInfer".format(SERVICE),
        request_serializer=req_cls.SerializeToString,
        response_deserializer=resp_cls.FromString,
    )

    # identity_fp32 echoes FP32; demonstrate typed fp32_contents alongside
    # the int path on simple (typed int_contents carries INT8..INT32).
    data = np.arange(-8, 8, dtype=np.int8)
    as_int32 = data.astype(np.int32).reshape(1, 16)
    request = pb.ModelInferRequest(model_name="simple")
    for name in ("INPUT0", "INPUT1"):
        tensor = request.inputs.add()
        tensor.name = name
        tensor.datatype = "INT32"
        tensor.shape.extend([1, 16])
        tensor.contents.int_contents.extend(int(x) for x in as_int32.flat)

    response = infer(request)
    output0 = np.frombuffer(
        response.raw_output_contents[0], dtype=np.int32).reshape(1, 16)
    if not np.array_equal(output0, as_int32 + as_int32):
        print("FAILED: incorrect sum")
        sys.exit(1)
    channel.close()
    print("PASS: explicit int8-range contents")


if __name__ == "__main__":
    main()
