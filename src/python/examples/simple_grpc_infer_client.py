#!/usr/bin/env python3
"""Sync infer on the `simple` add/sub model over gRPC (role of reference
src/python/examples/simple_grpc_infer_client.py)."""

import argparse
import sys

import numpy as np

import tritonclient.grpc as grpcclient
from tritonclient.utils import InferenceServerException


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-v", "--verbose", action="store_true")
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    client = grpcclient.InferenceServerClient(
        url=args.url, verbose=args.verbose
    )

    input0_data = np.arange(16, dtype=np.int32).reshape(1, 16)
    input1_data = np.full((1, 16), 1, dtype=np.int32)
    inputs = [
        grpcclient.InferInput("INPUT0", [1, 16], "INT32"),
        grpcclient.InferInput("INPUT1", [1, 16], "INT32"),
    ]
    inputs[0].set_data_from_numpy(input0_data)
    inputs[1].set_data_from_numpy(input1_data)
    outputs = [
        grpcclient.InferRequestedOutput("OUTPUT0"),
        grpcclient.InferRequestedOutput("OUTPUT1"),
    ]

    try:
        result = client.infer("simple", inputs, outputs=outputs)
    except InferenceServerException as e:
        print("inference failed: " + str(e))
        sys.exit(1)

    output0_data = result.as_numpy("OUTPUT0")
    output1_data = result.as_numpy("OUTPUT1")
    if not np.array_equal(output0_data, input0_data + input1_data):
        print("error: incorrect sum")
        sys.exit(1)
    if not np.array_equal(output1_data, input0_data - input1_data):
        print("error: incorrect difference")
        sys.exit(1)
    print("0 + 1 = {}".format(output0_data[0][0]))
    print("0 - 1 = {}".format(output1_data[0][0]))
    client.close()
    print("PASS: infer")


if __name__ == "__main__":
    main()
