#!/usr/bin/env python3
"""Image classification client: preprocessing (NONE/VGG/INCEPTION
scaling), batching, sync/async/streaming issue, classification
postprocessing — over HTTP or gRPC (role of reference
src/python/examples/image_client.py and the C++ image_client.cc:64-120).

Inputs may be .npy arrays, binary PPM (P6) images, or --synthetic random
images; images of other sizes are resampled (nearest neighbor) to the
model's 224x224 input.
"""

import argparse
import os
import queue
import sys

import numpy as np


def read_ppm(path):
    """Minimal binary-PPM (P6) reader -> uint8 HWC array."""
    with open(path, "rb") as f:
        data = f.read()
    if not data.startswith(b"P6"):
        raise ValueError("not a binary PPM (P6) file: " + path)
    fields = []
    pos = 2
    while len(fields) < 3:
        while pos < len(data) and data[pos : pos + 1].isspace():
            pos += 1
        if pos >= len(data):
            raise ValueError("truncated PPM header: " + path)
        if data[pos : pos + 1] == b"#":  # comment line
            newline = data.find(b"\n", pos)
            if newline < 0:
                raise ValueError("truncated PPM header: " + path)
            pos = newline + 1
            continue
        end = pos
        while end < len(data) and not data[end : end + 1].isspace():
            end += 1
        if end >= len(data):
            raise ValueError("truncated PPM header: " + path)
        fields.append(int(data[pos:end]))
        pos = end
    pos += 1  # single whitespace after maxval
    width, height, maxval = fields
    if maxval != 255:
        raise ValueError("only maxval=255 PPM supported")
    pixels = np.frombuffer(
        data, dtype=np.uint8, count=width * height * 3, offset=pos
    )
    return pixels.reshape(height, width, 3)


def load_image(path):
    if path.endswith(".npy"):
        return np.load(path)
    return read_ppm(path)


def resize_nearest(img, height, width):
    """Nearest-neighbor resample to (height, width, C)."""
    h, w = img.shape[:2]
    rows = (np.arange(height) * (h / height)).astype(np.int64)
    cols = (np.arange(width) * (w / width)).astype(np.int64)
    return img[rows][:, cols]


def preprocess(img, scaling, dtype=np.float32):
    """Scale pixel values per the requested scheme (reference
    image_client.cc:64-120: NONE, VGG mean-subtraction, INCEPTION
    [-1, 1])."""
    if img.ndim == 2:
        img = np.stack([img] * 3, axis=-1)
    img = resize_nearest(img, 224, 224).astype(np.float32)
    if scaling == "INCEPTION":
        scaled = (img / 127.5) - 1.0
    elif scaling == "VGG":
        scaled = img - np.array([123.68, 116.78, 103.94], np.float32)
    else:
        scaled = img
    return scaled.astype(dtype)


def parse_classes(class_bytes):
    """'value:index[:label]' entries -> (value, index, label) tuples."""
    out = []
    for entry in np.asarray(class_bytes).reshape(-1):
        parts = entry.decode("utf-8").split(":")
        out.append(
            (float(parts[0]), int(parts[1]),
             parts[2] if len(parts) > 2 else "")
        )
    return out


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-v", "--verbose", action="store_true")
    parser.add_argument("-m", "--model-name", default="resnet50")
    parser.add_argument("-x", "--model-version", default="")
    parser.add_argument("-b", "--batch-size", type=int, default=1)
    parser.add_argument("-c", "--classes", type=int, default=1,
                        help="number of class results to report")
    parser.add_argument("-s", "--scaling", default="NONE",
                        choices=["NONE", "VGG", "INCEPTION"])
    parser.add_argument("-u", "--url", default="localhost:8000")
    parser.add_argument("-i", "--protocol", default="HTTP",
                        choices=["HTTP", "GRPC", "http", "grpc"])
    parser.add_argument("-a", "--async", dest="async_set",
                        action="store_true",
                        help="issue requests asynchronously")
    parser.add_argument("--streaming", action="store_true",
                        help="issue via the gRPC bidi stream")
    parser.add_argument("--synthetic", type=int, default=0,
                        help="use N synthetic images instead of files")
    parser.add_argument("image_filename", nargs="*",
                        help=".npy or binary .ppm image files")
    args = parser.parse_args()

    protocol = args.protocol.lower()
    if args.streaming and protocol != "grpc":
        print("error: streaming requires the gRPC protocol")
        sys.exit(1)

    if protocol == "grpc":
        import tritonclient.grpc as tclient
    else:
        import tritonclient.http as tclient
    client = tclient.InferenceServerClient(
        url=args.url, verbose=args.verbose)

    # model metadata drives input naming/validation
    metadata = client.get_model_metadata(
        args.model_name, args.model_version)
    if protocol == "grpc":
        input_meta = metadata.inputs[0]
        input_name, input_dtype = input_meta.name, input_meta.datatype
        output_name = metadata.outputs[0].name
    else:
        input_meta = metadata["inputs"][0]
        input_name, input_dtype = input_meta["name"], input_meta["datatype"]
        output_name = metadata["outputs"][0]["name"]

    np_dtype = {"FP32": np.float32, "UINT8": np.uint8}.get(
        input_dtype, np.float32)

    if args.synthetic:
        rng = np.random.RandomState(0)
        raw_images = [
            (rng.rand(224, 224, 3) * 255).astype(np.uint8)
            for _ in range(args.synthetic)
        ]
        names = ["synthetic_{}".format(i) for i in range(args.synthetic)]
    else:
        if not args.image_filename:
            print("error: no input images (pass files or --synthetic N)")
            sys.exit(1)
        raw_images = [load_image(p) for p in args.image_filename]
        names = [os.path.basename(p) for p in args.image_filename]

    batches = []
    for start in range(0, len(raw_images), args.batch_size):
        chunk = raw_images[start : start + args.batch_size]
        batch = np.stack(
            [preprocess(img, args.scaling, np_dtype) for img in chunk]
        )
        batches.append((batch, names[start : start + args.batch_size]))

    outputs_of = lambda: [
        tclient.InferRequestedOutput(output_name)
        if args.classes == 0
        else _requested_output(tclient, output_name, args.classes,
                               protocol)
    ]

    responses = []
    if args.streaming:
        completed = queue.Queue()
        client.start_stream(
            callback=lambda result, error: completed.put((result, error)))
        try:
            for batch, batch_names in batches:
                inp = tclient.InferInput(
                    input_name, list(batch.shape), input_dtype)
                inp.set_data_from_numpy(batch)
                client.async_stream_infer(
                    args.model_name, [inp], outputs=outputs_of())
            for _, batch_names in batches:
                result, error = completed.get(timeout=120)
                if error is not None:
                    print("streaming infer failed: " + str(error))
                    sys.exit(1)
                responses.append((result, batch_names))
        finally:
            client.stop_stream()
    elif args.async_set:
        if protocol == "grpc":
            completed = queue.Queue()
            for batch, batch_names in batches:
                inp = tclient.InferInput(
                    input_name, list(batch.shape), input_dtype)
                inp.set_data_from_numpy(batch)
                client.async_infer(
                    args.model_name, [inp],
                    callback=(
                        lambda ns: lambda result, error: completed.put(
                            (result, error, ns))
                    )(batch_names),
                    outputs=outputs_of(),
                )
            for _ in batches:
                result, error, batch_names = completed.get(timeout=120)
                if error is not None:
                    print("async infer failed: " + str(error))
                    sys.exit(1)
                responses.append((result, batch_names))
        else:
            futures = []
            for batch, batch_names in batches:
                inp = tclient.InferInput(
                    input_name, list(batch.shape), input_dtype)
                inp.set_data_from_numpy(batch)
                futures.append(
                    (client.async_infer(args.model_name, [inp],
                                        outputs=outputs_of()),
                     batch_names))
            for fut, batch_names in futures:
                responses.append((fut.get_result(), batch_names))
    else:
        for batch, batch_names in batches:
            inp = tclient.InferInput(
                input_name, list(batch.shape), input_dtype)
            inp.set_data_from_numpy(batch)
            responses.append(
                (client.infer(args.model_name, [inp],
                              model_version=args.model_version,
                              outputs=outputs_of()),
                 batch_names))

    for result, batch_names in responses:
        output = result.as_numpy(output_name)
        if args.classes > 0:
            per_image = output.reshape(len(batch_names), -1)
            for name, row in zip(batch_names, per_image):
                print("Image '{}':".format(name))
                for value, index, label in parse_classes(row):
                    print("    {} ({}) = {}".format(index, label, value))
        else:
            print("Image batch {}: output shape {}".format(
                batch_names, output.shape))
    client.close()
    print("PASS: image client")


def _requested_output(tclient, name, classes, protocol):
    if protocol == "grpc":
        return tclient.InferRequestedOutput(name, class_count=classes)
    return tclient.InferRequestedOutput(
        name, binary_data=True, class_count=classes)


if __name__ == "__main__":
    main()
