#!/usr/bin/env python3
"""Callback-style async infer over gRPC (role of reference
src/python/examples/simple_grpc_async_infer_client.py)."""

import argparse
import queue
import sys

import numpy as np

import tritonclient.grpc as grpcclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-v", "--verbose", action="store_true")
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    client = grpcclient.InferenceServerClient(
        url=args.url, verbose=args.verbose
    )

    input0_data = np.arange(16, dtype=np.int32).reshape(1, 16)
    input1_data = np.full((1, 16), 5, dtype=np.int32)
    inputs = [
        grpcclient.InferInput("INPUT0", [1, 16], "INT32"),
        grpcclient.InferInput("INPUT1", [1, 16], "INT32"),
    ]
    inputs[0].set_data_from_numpy(input0_data)
    inputs[1].set_data_from_numpy(input1_data)

    completed = queue.Queue()
    n_requests = 8
    for _ in range(n_requests):
        client.async_infer(
            "simple", inputs,
            callback=lambda result, error: completed.put((result, error)),
        )
    for _ in range(n_requests):
        result, error = completed.get(timeout=30)
        if error is not None:
            print("inference failed: " + str(error))
            sys.exit(1)
        if not np.array_equal(
            result.as_numpy("OUTPUT0"), input0_data + input1_data
        ):
            print("error: incorrect sum")
            sys.exit(1)
    client.close()
    print("PASS: async infer")


if __name__ == "__main__":
    main()
