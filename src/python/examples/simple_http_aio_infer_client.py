#!/usr/bin/env python3
"""asyncio infer over HTTP (role of reference
simple_http_aio_infer_client.py)."""

import argparse
import asyncio
import sys

import numpy as np

import tritonclient.http.aio as httpclient


async def run(args):
    async with httpclient.InferenceServerClient(
        url=args.url, verbose=args.verbose
    ) as client:
        if not await client.is_server_live():
            print("FAILED: server not live")
            sys.exit(1)

        input0_data = np.arange(16, dtype=np.int32).reshape(1, 16)
        input1_data = np.full((1, 16), 1, dtype=np.int32)
        inputs = [
            httpclient.InferInput("INPUT0", [1, 16], "INT32"),
            httpclient.InferInput("INPUT1", [1, 16], "INT32"),
        ]
        inputs[0].set_data_from_numpy(input0_data, binary_data=True)
        inputs[1].set_data_from_numpy(input1_data, binary_data=True)

        result = await client.infer("simple", inputs)
        if not np.array_equal(
            result.as_numpy("OUTPUT0"), input0_data + input1_data
        ):
            print("FAILED: incorrect sum")
            sys.exit(1)
        if not np.array_equal(
            result.as_numpy("OUTPUT1"), input0_data - input1_data
        ):
            print("FAILED: incorrect difference")
            sys.exit(1)
    print("PASS: aio infer")


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-v", "--verbose", action="store_true")
    parser.add_argument("-u", "--url", default="localhost:8000")
    asyncio.run(run(parser.parse_args()))


if __name__ == "__main__":
    main()
