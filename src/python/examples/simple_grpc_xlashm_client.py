#!/usr/bin/env python3
"""TPU shared-memory data plane over gRPC: inputs are jax.Arrays placed
in an XLA (TPU HBM) region; outputs land in a second region and are read
back as numpy or jax (TPU-native role of reference
simple_grpc_cudashm_client.py — cudaMalloc/cudaIpc handle passing,
reference src/c++/examples/simple_grpc_cudashm_client.cc:180-250)."""

import argparse
import sys

import numpy as np

import tritonclient.grpc as grpcclient
from tritonclient.utils import xla_shared_memory as xshm


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-v", "--verbose", action="store_true")
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    import jax.numpy as jnp

    client = grpcclient.InferenceServerClient(
        url=args.url, verbose=args.verbose
    )
    client.unregister_xla_shared_memory()

    input0_data = jnp.asarray(
        np.arange(16, dtype=np.int32).reshape(1, 16))
    input1_data = jnp.asarray(np.full((1, 16), 1, dtype=np.int32))
    byte_size = 16 * 4

    shm_ip_handle = xshm.create_shared_memory_region(
        "input_data", byte_size * 2)
    shm_op_handle = xshm.create_shared_memory_region(
        "output_data", byte_size * 2)
    try:
        client.register_xla_shared_memory(
            "input_data", xshm.get_raw_handle(shm_ip_handle), 0,
            byte_size * 2)
        client.register_xla_shared_memory(
            "output_data", xshm.get_raw_handle(shm_op_handle), 0,
            byte_size * 2)
        xshm.set_shared_memory_region_from_jax(
            shm_ip_handle, [input0_data, input1_data])

        inputs = [
            grpcclient.InferInput("INPUT0", [1, 16], "INT32"),
            grpcclient.InferInput("INPUT1", [1, 16], "INT32"),
        ]
        inputs[0].set_shared_memory("input_data", byte_size)
        inputs[1].set_shared_memory("input_data", byte_size,
                                    offset=byte_size)
        outputs = [
            grpcclient.InferRequestedOutput("OUTPUT0"),
            grpcclient.InferRequestedOutput("OUTPUT1"),
        ]
        outputs[0].set_shared_memory("output_data", byte_size)
        outputs[1].set_shared_memory("output_data", byte_size,
                                     offset=byte_size)

        client.infer("simple", inputs, outputs=outputs)

        sum_jax = xshm.get_contents_as_jax(
            shm_op_handle, "INT32", [1, 16])
        diff_np = xshm.get_contents_as_numpy(
            shm_op_handle, np.int32, [1, 16], offset=byte_size)
        expected_sum = np.asarray(input0_data + input1_data)
        expected_diff = np.asarray(input0_data - input1_data)
        if not np.array_equal(np.asarray(sum_jax), expected_sum):
            print("FAILED: incorrect sum in xla shm")
            sys.exit(1)
        if not np.array_equal(diff_np, expected_diff):
            print("FAILED: incorrect difference in xla shm")
            sys.exit(1)
        status = client.get_xla_shared_memory_status()
        if len(status.regions) < 2:
            print("FAILED: xla shm status missing regions")
            sys.exit(1)
    finally:
        client.unregister_xla_shared_memory()
        xshm.destroy_shared_memory_region(shm_ip_handle)
        xshm.destroy_shared_memory_region(shm_op_handle)
    client.close()
    print("PASS: xla shared memory")


if __name__ == "__main__":
    main()
