#!/usr/bin/env python3
"""Stateful-sequence infer over HTTP: two interleaved sequences against
the `sequence_accumulate` model, each accumulating its own running sum
(role of reference simple_http_sequence_sync_infer_client.py — Triton
"sequences" are stateful inference streams, reference common.h:177-194)."""

import argparse
import sys

import numpy as np

import tritonclient.http as httpclient


def send(client, sequence_id, value, start=False, end=False):
    inp = httpclient.InferInput("INPUT", [1], "INT32")
    inp.set_data_from_numpy(np.array([value], dtype=np.int32))
    result = client.infer(
        "sequence_accumulate", [inp],
        sequence_id=sequence_id, sequence_start=start, sequence_end=end,
    )
    return int(result.as_numpy("OUTPUT")[0])


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-v", "--verbose", action="store_true")
    parser.add_argument("-u", "--url", default="localhost:8000")
    args = parser.parse_args()

    client = httpclient.InferenceServerClient(
        url=args.url, verbose=args.verbose
    )

    values = [11, 7, 5, 3, 2, 0, 1]
    seq0, seq1 = 1007, 1008
    acc0 = acc1 = 0
    for i, v in enumerate(values):
        start = i == 0
        end = i == len(values) - 1
        acc0 = send(client, seq0, v, start=start, end=end)
        acc1 = send(client, seq1, -v, start=start, end=end)
    expected = sum(values)
    print("sequence {}: {}".format(seq0, acc0))
    print("sequence {}: {}".format(seq1, acc1))
    if acc0 != expected or acc1 != -expected:
        print("FAILED: wrong accumulated values")
        sys.exit(1)
    client.close()
    print("PASS: sequence sync")


if __name__ == "__main__":
    main()
