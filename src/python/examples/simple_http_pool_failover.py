#!/usr/bin/env python3
"""Multi-replica HTTP client demo: an EndpointPool over two in-process
servers rides out a mid-traffic drain of one replica with zero
user-visible errors, and the drained replica's circuit breaker
re-closes once it returns to ready.

Self-contained: the two replicas are spun up in-process (a drain demo
needs a replica it is allowed to drain), so no external server is
required.  ``-u`` is accepted for harness compatibility and ignored.
"""

import argparse
import sys
import time

import numpy as np

import tritonclient.http as httpclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-v", "--verbose", action="store_true")
    parser.add_argument("-u", "--url", default=None,
                        help="ignored: this demo drains one of its own "
                             "in-process replicas")
    parser.add_argument("-n", "--requests", type=int, default=40)
    args = parser.parse_args()

    from tpuserver.core import InferenceServer
    from tpuserver.http_frontend import HttpFrontend
    from tpuserver.models.simple import SimpleModel

    cores = [InferenceServer([SimpleModel()]) for _ in range(2)]
    frontends = [HttpFrontend(core, port=0).start() for core in cores]
    urls = ["127.0.0.1:{}".format(f.port) for f in frontends]
    print("replicas: {}".format(urls))

    pool = httpclient.EndpointPool(
        urls,
        verbose=args.verbose,
        retry_policy=httpclient.RetryPolicy(
            max_attempts=6, initial_backoff_s=0.02),
        breaker_threshold=2,
        breaker_cooldown_s=0.2,
        health_interval_s=0.05,  # background readiness probing
    )

    data = np.arange(16, dtype=np.int32).reshape(1, 16)

    def make_inputs():
        inputs = [
            httpclient.InferInput("INPUT0", [1, 16], "INT32"),
            httpclient.InferInput("INPUT1", [1, 16], "INT32"),
        ]
        inputs[0].set_data_from_numpy(data)
        inputs[1].set_data_from_numpy(data)
        return inputs

    errors = 0
    for i in range(args.requests):
        if i == args.requests // 3:
            print("--- draining replica B mid-traffic ---")
            cores[1].begin_drain()
        try:
            result = pool.infer("simple", make_inputs())
            if not np.array_equal(result.as_numpy("OUTPUT0"), data + data):
                print("wrong result at request {}".format(i))
                errors += 1
        except Exception as e:  # noqa: BLE001 — counted as a failure
            print("request {} failed: {}".format(i, e))
            errors += 1

    print("drained-phase breaker states: {}".format(pool.endpoint_states()))
    print("--- replica B returns to ready (undrain) ---")
    cores[1].mark_ready()

    def replica_b():
        return [e for e in pool.stats()["endpoints"]
                if e["url"] == urls[1]][0]

    # the background prober notices recovery: breaker re-closes (if it
    # opened) and the health flag flips back
    deadline = time.monotonic() + 5.0
    while (
        not (replica_b()["healthy"] and replica_b()["breaker"] == "closed")
        and time.monotonic() < deadline
    ):
        time.sleep(0.05)
    print("recovered breaker states:     {}".format(pool.endpoint_states()))

    for _ in range(6):  # both replicas take traffic again
        pool.infer("simple", make_inputs())
    stats = pool.stats()
    for entry in stats["endpoints"]:
        print("endpoint {url}: requests={requests} failures={failures} "
              "healthy={healthy} breaker={breaker}".format(**entry))

    pool.close()
    for f in frontends:
        f.stop()

    if errors:
        print("FAIL: {} request(s) failed through the pool".format(errors))
        sys.exit(1)
    if stats["endpoints"][1]["breaker"] != "closed":
        print("FAIL: drained replica's breaker did not re-close")
        sys.exit(1)
    print("PASS: drain was invisible to pool callers")


if __name__ == "__main__":
    main()
