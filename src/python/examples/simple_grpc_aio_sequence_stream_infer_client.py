#!/usr/bin/env python3
"""asyncio bidirectional streaming with stateful sequences: the
stream_infer async generator consumes an async iterator of requests
(role of reference simple_grpc_aio_sequence_stream_infer_client.py)."""

import argparse
import asyncio
import sys

import numpy as np

import tritonclient.grpc.aio as grpcclient


async def run(args):
    values = [11, 7, 5, 3, 2, 0, 1]
    sequence_id = 4007

    async def requests():
        for i, v in enumerate(values):
            inp = grpcclient.InferInput("INPUT", [1], "INT32")
            inp.set_data_from_numpy(np.array([v], dtype=np.int32))
            yield {
                "model_name": "sequence_accumulate",
                "inputs": [inp],
                "request_id": "seq_{}".format(i),
                "sequence_id": sequence_id,
                "sequence_start": i == 0,
                "sequence_end": i == len(values) - 1,
            }

    async with grpcclient.InferenceServerClient(url=args.url) as client:
        partial_sums = []
        async for result, error in client.stream_infer(requests()):
            if error is not None:
                print("stream error: " + str(error))
                sys.exit(1)
            partial_sums.append(int(result.as_numpy("OUTPUT")[0]))

    expected = []
    acc = 0
    for v in values:
        acc += v
        expected.append(acc)
    print("partial sums: {}".format(partial_sums))
    if partial_sums != expected:
        print("FAILED: wrong partial sums")
        sys.exit(1)
    print("PASS: aio sequence stream")


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-v", "--verbose", action="store_true")
    parser.add_argument("-u", "--url", default="localhost:8001")
    asyncio.run(run(parser.parse_args()))


if __name__ == "__main__":
    main()
