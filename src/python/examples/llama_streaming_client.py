#!/usr/bin/env python3
"""Token-by-token generation over the decoupled gRPC stream, with the KV
cache parked in a TPU (XLA) shared-memory region between requests — the
LLM-shaped client of BASELINE config #5 (decoupled ModelStreamInfer +
TPU-shm KV-handle passing; reference's closest analogue is
simple_grpc_custom_repeat.py plus CUDA-shm tensor passing).

Generation rides ``client.generate_stream``, the auto-resuming helper:
if the stream connection drops mid-generation the client transparently
re-opens it with a resume token and the server (a continuous-batching
replica) replays the missed tokens and splices the continuation — no
duplicated or missing tokens (docs/resilience.md, "Self-healing &
stream resume")."""

import argparse
import sys

import numpy as np

import tritonclient.grpc as grpcclient
from tritonclient.utils import xla_shared_memory as xshm


def generate(client, prompt, max_tokens, parameters=None):
    p_in = grpcclient.InferInput("PROMPT_IDS", [len(prompt)], "INT32")
    p_in.set_data_from_numpy(np.asarray(prompt, dtype=np.int32))
    m_in = grpcclient.InferInput("MAX_TOKENS", [1], "INT32")
    m_in.set_data_from_numpy(np.array([max_tokens], dtype=np.int32))
    tokens = []
    # generate_stream auto-resumes a dropped connection (same endpoint);
    # on_reconnect is just visibility into how bumpy the ride was
    for result in client.generate_stream(
            "llama_generate", [p_in, m_in], parameters=parameters,
            on_reconnect=lambda attempt, exc: print(
                "reconnect {} after: {}".format(attempt, exc),
                flush=True)):
        token = int(result.as_numpy("TOKEN")[0])
        tokens.append(token)
        print("token:", token, flush=True)
    return tokens


def generate_shm(client, prompt, max_tokens):
    """The zero-copy data plane: PROMPT_IDS travels as a shared-memory
    reference and every generated TOKEN/LOGPROB lands in a token-ring
    slot of the same region — the decoupled responses shrink to
    ``seq -> offset`` descriptors and this side reads the ring."""
    prompt = np.asarray(prompt, dtype=np.int32)
    ring_base = 64  # prompt at offset 0, ring slots (8 B each) above
    region = xshm.create_shared_memory_region(
        "llama_shm_plane", ring_base + 8 * max_tokens)
    xshm.set_shared_memory_region(region, [prompt])
    client.register_xla_shared_memory(
        "llama_shm_plane", xshm.get_raw_handle(region), 0,
        ring_base + 8 * max_tokens)
    try:
        p_in = grpcclient.InferInput("PROMPT_IDS", [len(prompt)], "INT32")
        p_in.set_shared_memory("llama_shm_plane", prompt.nbytes, 0)
        m_in = grpcclient.InferInput("MAX_TOKENS", [1], "INT32")
        m_in.set_data_from_numpy(np.array([max_tokens], dtype=np.int32))
        events = 0
        for _result in client.generate_stream(
                "llama_generate", [p_in, m_in],
                parameters={"shm_ring_region": "llama_shm_plane",
                            "shm_ring_slots": max_tokens,
                            "shm_ring_offset": ring_base}):
            events += 1  # descriptor-only event; tensors are in the ring
        tokens = [
            int(xshm.get_contents_as_numpy(
                region, "INT32", [1], ring_base + 8 * s)[0])
            for s in range(events)
        ]
        print("ring tokens:", tokens, flush=True)
        return tokens
    finally:
        client.unregister_xla_shared_memory("llama_shm_plane")
        xshm.destroy_shared_memory_region(region)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    parser.add_argument("-n", "--max-tokens", type=int, default=8)
    parser.add_argument("--shared-memory", default="none",
                        choices=["none", "xla"],
                        help="xla: send the prompt by shm reference and "
                             "read TOKEN/LOGPROB from a token ring in "
                             "the same region (zero-copy in-process; "
                             "host-window staging cross-process)")
    args = parser.parse_args()

    client = grpcclient.InferenceServerClient(args.url)

    if args.shared_memory == "xla":
        prompt = [1, 5, 9, 13]
        try:
            # token identity across planes: the in-band stream and the
            # shm-ring stream must carry the same greedy tokens
            inband = generate(client, prompt, args.max_tokens)
            ring = generate_shm(client, prompt, args.max_tokens)
            if ring != inband:
                print("FAILED: ring tokens diverged from in-band")
                sys.exit(1)
        finally:
            client.close()
        print("PASS: llama streaming (xla shared memory)")
        return

    kv = xshm.create_shared_memory_region("llama_kv_park", 16 << 20)
    client.register_xla_shared_memory(
        "llama_kv_park", xshm.get_raw_handle(kv), 0, 16 << 20)
    try:
        prompt = [1, 5, 9, 13]
        # first pass: prefill + generate, parking the finished KV cache
        # (which then holds prompt + the generated tokens)
        first = generate(
            client, prompt, args.max_tokens,
            parameters={"kv_cache_region": "llama_kv_park"})
        # resumed pass: the parked cache already contains the history, so
        # send ONLY the new continuation tokens with the position the
        # cache was left at — no re-prefill of the earlier sequence
        follow_up = [2, 6]
        resumed = generate(
            client, follow_up, args.max_tokens,
            parameters={"kv_cache_region": "llama_kv_park",
                        "kv_cache_resume": True,
                        "kv_cache_position": len(prompt) + len(first)})
        print("first:", first)
        print("resumed:", resumed)
        if len(first) != args.max_tokens or len(resumed) != args.max_tokens:
            print("FAILED: wrong token counts")
            sys.exit(1)
    finally:
        client.unregister_xla_shared_memory("llama_kv_park")
        xshm.destroy_shared_memory_region(kv)
        client.close()
    print("PASS: llama streaming")


if __name__ == "__main__":
    main()
