"""Deprecated alias of :mod:`tritonclient.grpc` (role of reference
src/python/library/tritongrpcclient/__init__.py:26-35 — kept so pre-rename imports
keep working, with a DeprecationWarning)."""

import warnings

warnings.warn(
    "The package `tritongrpcclient` is deprecated; use `tritonclient.grpc` "
    "instead.",
    DeprecationWarning,
    stacklevel=2,
)

from tritonclient.grpc import *  # noqa: F401,F403,E402
from tritonclient.grpc import InferenceServerClient  # noqa: F401,E402
from tritonclient.utils import (  # noqa: F401,E402
    InferenceServerException,
    np_to_triton_dtype,
    triton_to_np_dtype,
)
