"""Request-arrival schedules for the request-rate load manager.

Role of the reference's ``ScheduleDistribution`` (perf_utils.h:152):
one shared generator of inter-arrival gaps that both the profiler's
sweep and the deterministic unit tests consume — pure math, no clocks,
no threads.
"""

import random


def schedule_distribution(distribution, rate, seed=0):
    """Infinite generator of inter-arrival gaps (seconds) at ``rate``
    requests/second.

    ``distribution`` is ``"constant"`` (every gap exactly ``1/rate`` —
    a metronome) or ``"poisson"`` (exponentially distributed gaps with
    mean ``1/rate`` — memoryless arrivals, the open-loop traffic model).
    The Poisson stream is seeded, so a given ``(rate, seed)`` pair
    always produces the same schedule (measurements are repeatable and
    the unit tests are exact).
    """
    if rate <= 0:
        raise ValueError(
            "schedule rate must be positive (got {})".format(rate))
    if distribution == "constant":
        gap = 1.0 / rate
        while True:
            yield gap
    elif distribution == "poisson":
        rng = random.Random(seed)
        while True:
            yield rng.expovariate(rate)
    else:
        raise ValueError(
            "unknown schedule distribution '{}' (want 'constant' or "
            "'poisson')".format(distribution))


def take_gaps(distribution, rate, count, seed=0):
    """First ``count`` gaps of a schedule, as a list (test/helper form)."""
    gen = schedule_distribution(distribution, rate, seed)
    return [next(gen) for _ in range(count)]
