"""Load managers: closed-loop concurrency and open-loop request rate.

Role of the reference's ``ConcurrencyManager`` / ``RequestRateManager``
(concurrency_manager.h:90, request_rate_manager.h; worker loop idiom of
concurrency_worker.cc:153-257): the concurrency manager maintains
exactly N requests in flight via a context free-list replenished by
completion callbacks; the request-rate manager sends on a fixed
schedule REGARDLESS of completions (open loop — what a real traffic
source does), with constant or Poisson gaps from
:func:`perfanalyzer.schedule.schedule_distribution`.

Both record completions into a :class:`LoadCollector`, which the
profiler windows over.
"""

import sys
import threading
import time

from perfanalyzer.schedule import schedule_distribution


class LoadCollector:
    """Thread-safe completion sink with measurement-window gating.

    Completions that land outside an open window are dropped — the
    profiler only ever reasons about requests that completed inside the
    window it is measuring (reference ``TimestampVector`` semantics).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._open = False       # guarded-by: _lock
        self._latencies = []     # guarded-by: _lock
        self._errors = 0         # guarded-by: _lock
        self._completions = 0    # guarded-by: _lock
        self._cond = threading.Condition(self._lock)

    def start_window(self):
        with self._lock:
            self._open = True
            self._latencies = []
            self._errors = 0
            self._completions = 0

    def end_window(self):
        """Close the window; returns ``(latencies_s, error_count)``."""
        with self._lock:
            self._open = False
            return self._latencies, self._errors

    def record(self, start_s, end_s, error):
        with self._lock:
            if not self._open:
                return
            self._completions += 1
            if error is None:
                self._latencies.append(end_s - start_s)
            else:
                self._errors += 1
            self._cond.notify_all()

    def wait_for_completions(self, count, timeout_s, early_exit=None):
        """Block until ``count`` completions land in the open window
        (count-windows measurement mode); returns the elapsed seconds.
        ``early_exit`` (a ``threading.Event``) truncates the wait —
        the two-stage SIGINT path."""
        t0 = time.perf_counter()
        deadline = t0 + timeout_s
        with self._lock:
            while self._completions < count:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                if early_exit is not None and early_exit.is_set():
                    break
                self._cond.wait(min(0.05, remaining))
        return time.perf_counter() - t0


class ConcurrencyManager:
    """Keeps exactly N requests in flight against one model.

    N *contexts* each own a rotating cursor into the prepared-request
    pool (distinct inputs per dispatch — hygiene rule 1).  Free context
    ids sit on a free-list; a dispatcher thread pops one, dispatches
    via ``backend.submit``, and the completion callback records the
    latency and pushes the id back — the reference's
    ``concurrency_worker.cc`` free-list + callback-wakeup shape, which
    holds the in-flight count at N without one thread per request.
    """

    mode = "concurrency"

    def __init__(self, backend, model, prepared, collector=None):
        if not prepared:
            raise ValueError("need at least one prepared request")
        self.backend = backend
        self.model = model
        self.prepared = list(prepared)
        self.collector = collector or LoadCollector()
        self._cond = threading.Condition()
        self._free = 0    # contexts on the free-list  # guarded-by: _cond
        # contexts in circulation (free + in flight)  # guarded-by: _cond
        self._live = 0
        self._target = 0    # guarded-by: _cond
        self._inflight = 0  # guarded-by: _cond
        self._stopping = False  # guarded-by: _cond
        self._cursor = 0    # guarded-by: _cond
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop,
            name="perfanalyzer-concurrency-dispatch", daemon=True)
        self._dispatcher.start()

    # -- the load level knob ----------------------------------------------

    def change_level(self, concurrency):
        """Reconfigure to exactly ``concurrency`` in-flight requests.

        Growing mints new contexts onto the free-list; shrinking drops
        free contexts immediately and retires in-flight ones as they
        complete (no cancellation — the reference drains the same
        way).  Levels may move in any order: contexts are fungible
        counters, so a shrink-then-grow re-mints what it needs."""
        if concurrency < 1:
            raise ValueError(
                "concurrency must be >= 1 (got {})".format(concurrency))
        with self._cond:
            self._target = int(concurrency)
            while self._live < self._target:
                self._free += 1
                self._live += 1
            while self._free > 0 and self._live > self._target:
                self._free -= 1
                self._live -= 1
            self._cond.notify_all()

    def _dispatch_loop(self):
        while True:
            with self._cond:
                while not self._stopping and not (
                        self._free > 0 and self._target > 0):
                    self._cond.wait()
                if self._stopping:
                    return
                self._free -= 1
                self._inflight += 1
                req = self.prepared[self._cursor % len(self.prepared)]
                self._cursor += 1
            start = time.perf_counter()

            def on_done(error, start=start):
                self.collector.record(start, time.perf_counter(), error)
                with self._cond:
                    self._inflight -= 1
                    if self._stopping or self._live > self._target:
                        self._live -= 1  # retire this context
                    else:
                        self._free += 1
                    self._cond.notify_all()

            try:
                self.backend.submit(req, on_done)
            except Exception as e:  # noqa: BLE001 — a failed dispatch
                # counts as a failed request, never a stuck context
                on_done(e)

    def inflight(self):
        with self._cond:
            return self._inflight

    def stop(self, timeout_s=30.0):
        """Stop dispatching and drain in-flight requests."""
        with self._cond:
            self._stopping = True
            self._target = 0
            self._cond.notify_all()
        self._dispatcher.join(timeout=5.0)
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(min(0.1, remaining))


class RequestRateManager:
    """Open-loop sender: dispatches on the schedule no matter what.

    The schedule (constant or Poisson gaps) is laid out as absolute
    send times from the epoch of ``change_level``; the sender thread
    walks it, dispatching through ``backend.submit`` without waiting
    for completions — queueing delay under overload therefore shows up
    in the measured latency, which is the whole point of rate mode.
    """

    mode = "request_rate"

    def __init__(self, backend, model, prepared, distribution="constant",
                 seed=0, collector=None):
        if not prepared:
            raise ValueError("need at least one prepared request")
        self.backend = backend
        self.model = model
        self.prepared = list(prepared)
        self.distribution = distribution
        self.seed = seed
        self.collector = collector or LoadCollector()
        self._sender = None
        self._stop_event = threading.Event()
        self._inflight = 0  # guarded-by: _inflight_lock
        self._inflight_lock = threading.Lock()
        self._capacity_warned = False  # guarded-by: _inflight_lock

    def change_level(self, rate):
        """(Re)start the sender at ``rate`` requests/second."""
        if rate <= 0:
            raise ValueError("rate must be > 0 (got {})".format(rate))
        self._stop_sender()
        self._stop_event = threading.Event()
        self._sender = threading.Thread(
            target=self._send_loop, args=(float(rate), self._stop_event),
            name="perfanalyzer-rate-sender", daemon=True)
        self._sender.start()

    def _send_loop(self, rate, stop_event):
        gaps = schedule_distribution(self.distribution, rate, self.seed)
        epoch = time.perf_counter()
        next_send = epoch
        cursor = 0
        while not stop_event.is_set():
            next_send += next(gaps)
            while True:
                delay = next_send - time.perf_counter()
                if delay <= 0:
                    break
                if stop_event.wait(min(delay, 0.05)):
                    return
            req = self.prepared[cursor % len(self.prepared)]
            cursor += 1
            start = time.perf_counter()
            with self._inflight_lock:
                self._inflight += 1
                capacity = getattr(self.backend, "capacity", None)
                if (capacity is not None
                        and self._inflight >= capacity
                        and not self._capacity_warned):
                    # past this point dispatches queue INSIDE the
                    # backend and the loop is no longer open: the run
                    # stays valid for throughput but latencies include
                    # client-side queueing — say so once, loudly
                    self._capacity_warned = True
                    print(
                        "perf_analyzer warning: outstanding requests "
                        "reached the backend capacity ({}); the "
                        "schedule is no longer open-loop — resize "
                        "with --max-outstanding".format(capacity),
                        file=sys.stderr, flush=True)

            def on_done(error, start=start):
                self.collector.record(start, time.perf_counter(), error)
                with self._inflight_lock:
                    self._inflight -= 1

            try:
                self.backend.submit(req, on_done)
            except Exception as e:  # noqa: BLE001 — a failed dispatch is
                # a failed request; the schedule marches on
                on_done(e)

    def inflight(self):
        with self._inflight_lock:
            return self._inflight

    def _stop_sender(self):
        if self._sender is not None:
            self._stop_event.set()
            self._sender.join(timeout=5.0)
            self._sender = None

    def stop(self, timeout_s=30.0):
        self._stop_sender()
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._inflight_lock:
                if self._inflight == 0:
                    return
            time.sleep(0.02)
