"""Generation mode: token-level metrics for decoupled/streaming models.

The serving-side scheduler (PR 1) exists to lift sustained generation
throughput; these are the client-side numbers that prove it: TTFT
(time-to-first-token), ITL (inter-token latency) percentiles, and
aggregate tokens/sec, measured over ``/generate_stream`` SSE, decoupled
gRPC streams, or the in-process core — whatever the backend speaks.

Same window/stability machinery as the scalar profiler: tokens are
counted the moment they ARRIVE (throughput is arrival-rate, not
completion-rate), while TTFT/ITL samples are attributed to the window
their generation completes in.
"""

import threading
import time

from perfanalyzer import metrics
from perfanalyzer.profiler import ProfileResult
from perfanalyzer.stability import StabilityDetector


class _GenCollector:
    """Window-gated sink for token arrivals + completed generations."""

    def __init__(self):
        self._lock = threading.Lock()
        self._open = False  # guarded-by: _lock
        # counted window-open or not  # guarded-by: _lock
        self._lifetime_generations = 0
        self._reset_locked()

    def _reset_locked(self):
        self._tokens = 0             # guarded-by: _lock
        self._ttfts = []             # guarded-by: _lock
        self._itls = []              # guarded-by: _lock
        self._generations = 0        # guarded-by: _lock
        self._errors = 0             # guarded-by: _lock
        self._resumed_streams = 0    # guarded-by: _lock
        self._resume_events = 0      # guarded-by: _lock

    def start_window(self):
        with self._lock:
            self._open = True
            self._reset_locked()

    def end_window(self):
        with self._lock:
            self._open = False
            return {
                "tokens": self._tokens,
                "ttfts_s": self._ttfts,
                "itls_s": self._itls,
                "generations": self._generations,
                "errors": self._errors,
                "resumed_streams": self._resumed_streams,
                "resume_events": self._resume_events,
            }

    def record_tokens(self, count):
        with self._lock:
            if self._open:
                self._tokens += count

    def lifetime_generations(self):
        with self._lock:
            return self._lifetime_generations

    def record_generation(self, ttft_s, itls_s, error, resumes=0):
        with self._lock:
            self._lifetime_generations += 1
            if not self._open:
                return
            if resumes:
                # a stream that reconnected mid-generation is counted
                # even when it ultimately errored: under-chaos perf runs
                # must surface the degradation, not hide it behind the
                # transparent splice
                self._resumed_streams += 1
                self._resume_events += resumes
            if error is not None:
                self._errors += 1
                return
            self._generations += 1
            if ttft_s is not None:
                self._ttfts.append(ttft_s)
            self._itls.extend(itls_s)


class GenerationProfiler:
    """Concurrency-mode load + windowed stability for streamed
    generation.

    N worker threads each run back-to-back generations (closed loop at
    the *stream* level — the continuous-batching scheduler keeps N
    slots busy), rotating DISTINCT prompts from the prepared pool.
    Stability is judged on tokens/sec and average ITL across
    ``stability_windows`` consecutive windows.
    """

    mode = "generation_concurrency"

    def __init__(self, backend, model, input_pool, parameters=None,
                 measurement_interval_s=1.0, stability_pct=10.0,
                 stability_windows=3, max_trials=10, warmup_s=0.0,
                 early_exit=None, verbose=False):
        if not backend.supports_generation:
            raise ValueError(
                "backend '{}' does not support generation mode".format(
                    backend.kind))
        if not input_pool:
            raise ValueError("need at least one prompt input set")
        self.backend = backend
        self.model = model
        self.input_pool = list(input_pool)
        # a callable builds per-stream parameters (the shm token-ring
        # mode hands every stream its own ring lane); a dict is shared
        self.parameters = (parameters if callable(parameters)
                           else dict(parameters or {}))
        self.measurement_interval_s = float(measurement_interval_s)
        self.stability_pct = float(stability_pct)
        self.stability_windows = int(stability_windows)
        self.max_trials = int(max_trials)
        self.warmup_s = float(warmup_s)
        self.early_exit = early_exit
        self.verbose = verbose
        self.collector = _GenCollector()
        self._workers = []
        self._level_baseline = 0
        self._stop_event = threading.Event()
        self._cursor_lock = threading.Lock()
        self._cursor = 0  # guarded-by: _cursor_lock

    # -- workers -----------------------------------------------------------

    def _next_inputs(self):
        with self._cursor_lock:
            inputs = self.input_pool[self._cursor % len(self.input_pool)]
            self._cursor += 1
        return inputs

    def _worker_loop(self, stop_event):
        try:
            while not stop_event.is_set():
                inputs = self._next_inputs()
                t0 = time.perf_counter()
                ttft = None
                prev = None
                itls = []
                error = None
                stream_stats = {}
                params = (self.parameters() if callable(self.parameters)
                          else self.parameters)
                try:
                    for count in self.backend.generate_stream(
                            self.model, inputs, params,
                            stats=stream_stats):
                        now = time.perf_counter()
                        if ttft is None:
                            ttft = now - t0
                        else:
                            itls.append(now - prev)
                        prev = now
                        self.collector.record_tokens(count)
                except Exception as e:  # noqa: BLE001 — a worker must
                    # never die silently mid-profile; the error (typed
                    # BackendError or not) is counted
                    error = e
                self.collector.record_generation(
                    ttft, itls, error,
                    resumes=stream_stats.get("resumes", 0))
        finally:
            self.backend.release_thread_resources()

    def _set_workers(self, concurrency):
        self._stop_workers()
        # baseline AFTER the old level's workers drained and BEFORE the
        # new ones start: the warmup gate must see a completion from
        # THIS level, not the previous level's final generations
        self._level_baseline = self.collector.lifetime_generations()
        self._stop_event = threading.Event()
        self._workers = [
            threading.Thread(
                target=self._worker_loop, args=(self._stop_event,),
                name="perfanalyzer-gen-{}".format(i), daemon=True)
            for i in range(concurrency)
        ]
        for w in self._workers:
            w.start()

    def _stop_workers(self):
        if self._workers:
            self._stop_event.set()
            # workers finish their CURRENT generation then exit; joining
            # bounds the wait so a wedged stream cannot hang the sweep
            for w in self._workers:
                w.join(timeout=120.0)
            self._workers = []

    # -- profiling ---------------------------------------------------------

    def change_level(self, concurrency):
        self._set_workers(int(concurrency))

    def _run_window(self):
        self.collector.start_window()
        t0 = time.perf_counter()
        deadline = t0 + self.measurement_interval_s
        while True:
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                break
            if self.early_exit is not None and self.early_exit.is_set():
                break
            time.sleep(min(0.05, remaining))
        duration = time.perf_counter() - t0
        window = self.collector.end_window()
        window["duration_s"] = duration
        return window

    def profile_level(self, level):
        self.change_level(level)
        # warmup waits for a COMPLETED generation at THIS level, not
        # just wall time: the first stream at a new level can carry XLA
        # compiles that dwarf every window (hygiene rule 5 — compiles
        # land outside measurement), then settles to the configured
        # warmup
        seen = self._level_baseline
        deadline = time.monotonic() + 120.0
        while (self.collector.lifetime_generations() <= seen
               and time.monotonic() < deadline):
            if self.early_exit is not None and self.early_exit.is_set():
                break
            time.sleep(0.02)
        if self.warmup_s > 0:
            if self.early_exit is not None:
                self.early_exit.wait(self.warmup_s)
            else:
                time.sleep(self.warmup_s)
        detector = StabilityDetector(
            self.stability_pct, self.stability_windows,
            check_latency=False)
        router_before = self.backend.router_snapshot()
        # radix prefix-cache counters (replica /metrics, or the fleet
        # aggregate through a router): the level delta becomes the
        # report's hit-rate column — post-warmup, so compile-time
        # admissions stay out of the rate
        prefix_before = self.backend.prefix_cache_snapshot()
        # speculative-decoding counters: the level delta becomes the
        # accepted-per-step and draft-hit-rate columns
        spec_before = self.backend.spec_snapshot()
        windows = []
        stable = False
        interrupted = False
        for trial in range(self.max_trials):
            window = self._run_window()
            if window["duration_s"] <= 0:
                continue
            windows.append(window)
            tok_rate = window["tokens"] / window["duration_s"]
            detector.add_window(tok_rate, 0.0)
            if self.verbose:
                print("  trial {:2d}: {:8.1f} tokens/sec".format(
                    trial + 1, tok_rate), flush=True)
            if self.early_exit is not None and self.early_exit.is_set():
                interrupted = True
                break
            if len(windows) >= self.stability_windows and detector.stable():
                stable = True
                break
        merged = windows[-self.stability_windows:]
        duration = sum(w["duration_s"] for w in merged)
        tokens = sum(w["tokens"] for w in merged)
        ttfts = [t for w in merged for t in w["ttfts_s"]]
        itls = [t for w in merged for t in w["itls_s"]]
        generations = sum(w["generations"] for w in merged)
        errors = sum(w["errors"] for w in merged)
        result = ProfileResult(
            mode=self.mode,
            level=level,
            stable=stable,
            interrupted=interrupted,
            trials=len(windows),
            throughput=tokens / duration if duration > 0 else 0.0,
            tokens=tokens,
            generations=generations,
            gen_per_sec=generations / duration if duration > 0 else 0.0,
            errors=errors,
            # streams that transparently reconnected+resumed mid-
            # generation (and the raw reconnect count): nonzero under
            # chaos means the transport is degrading even when every
            # token was ultimately delivered
            resumed_streams=sum(w["resumed_streams"] for w in merged),
            resume_events=sum(w["resume_events"] for w in merged),
            duration_s=duration,
        )
        metrics.attach_router_delta(result, router_before,
                                    self.backend.router_snapshot())
        prefix_after = self.backend.prefix_cache_snapshot()
        if prefix_before is not None and prefix_after is not None:
            # counters are cumulative and churn-safe (the router view
            # never decreases); max() guards a replaced plain replica
            dh = max(0, prefix_after["hits"] - prefix_before["hits"])
            dm = max(0, prefix_after["misses"] - prefix_before["misses"])
            result["prefix_cache_hits"] = dh
            result["prefix_cache_misses"] = dm
            result["prefix_hit_pct"] = (
                100.0 * dh / (dh + dm) if dh + dm else None)
        spec_after = self.backend.spec_snapshot()
        if spec_before is not None and spec_after is not None:
            ds = max(0, spec_after["steps"] - spec_before["steps"])
            dp = max(0, spec_after["proposed"] - spec_before["proposed"])
            da = max(0, spec_after["accepted"] - spec_before["accepted"])
            result["spec_steps"] = ds
            result["spec_proposed"] = dp
            result["spec_accepted"] = da
            # bonus + accepted drafts per speculative step (> 1 is the
            # win; None when the window never speculated)
            result["spec_accept_per_step"] = (
                (ds + da) / ds if ds else None)
            result["spec_hit_pct"] = (
                100.0 * da / dp if dp else None)
        for prefix, sample in (("ttft", ttfts), ("itl", itls)):
            if sample:
                ms = sorted(v * 1e3 for v in sample)
                result[prefix + "_avg_ms"] = sum(ms) / len(ms)
                for p in (50, 90, 95, 99):
                    result["{}_p{}_ms".format(prefix, p)] = (
                        metrics.percentile(ms, p, presorted=True))
            else:
                result[prefix + "_avg_ms"] = None
                for p in (50, 90, 95, 99):
                    result["{}_p{}_ms".format(prefix, p)] = None
        return result

    def sweep(self, levels):
        results = []
        try:
            for level in levels:
                if (self.early_exit is not None
                        and self.early_exit.is_set()):
                    break
                results.append(self.profile_level(level))
                if results[-1]["interrupted"]:
                    break
        finally:
            self.stop()
        return results

    def stop(self):
        self._stop_workers()
