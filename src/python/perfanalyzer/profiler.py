"""The windowed inference profiler.

Role of the reference's ``InferenceProfiler``
(inference_profiler.h:243-297, ProfileHelper at
inference_profiler.cc:670-778): per load level, repeat measurement
windows (time- or count-based) until the last three agree on throughput
and latency within the stability percentage, then merge those three
windows into one reported sample with client-side percentiles and a
server-side queue/compute breakdown diffed from
``get_inference_statistics()``.
"""

import time

from perfanalyzer import metrics
from perfanalyzer.stability import StabilityDetector


class ProfileResult(dict):
    """One load level's merged measurement (a plain dict with attribute
    sugar so report code reads cleanly)."""

    __getattr__ = dict.get


class InferenceProfiler:
    """Windows + stability + stat merging over one load manager.

    Parameters mirror the reference CLI: ``measurement_mode`` is
    ``"time_windows"`` (each window ``measurement_interval_s`` long) or
    ``"count_windows"`` (each window runs until
    ``measurement_request_count`` completions); ``stability_pct`` and
    ``max_trials`` bound the stability search; ``early_exit`` (a
    ``threading.Event``) is the two-stage-SIGINT hook — when set, the
    current window is cut short, reported as-is, and the sweep stops.
    """

    def __init__(self, backend, model, manager,
                 measurement_mode="time_windows",
                 measurement_interval_s=1.0,
                 measurement_request_count=50,
                 stability_pct=10.0, stability_windows=3, max_trials=10,
                 check_latency_stability=True, warmup_s=0.0,
                 early_exit=None, verbose=False):
        if measurement_mode not in ("time_windows", "count_windows"):
            raise ValueError(
                "measurement_mode must be time_windows or count_windows "
                "(got {!r})".format(measurement_mode))
        if max_trials < stability_windows:
            raise ValueError(
                "max_trials ({}) must be >= stability_windows ({})"
                .format(max_trials, stability_windows))
        self.backend = backend
        self.model = model
        self.manager = manager
        self.measurement_mode = measurement_mode
        self.measurement_interval_s = float(measurement_interval_s)
        self.measurement_request_count = int(measurement_request_count)
        self.stability_pct = float(stability_pct)
        self.stability_windows = int(stability_windows)
        self.max_trials = int(max_trials)
        self.check_latency_stability = bool(check_latency_stability)
        self.warmup_s = float(warmup_s)
        self.early_exit = early_exit
        self.verbose = verbose

    # -- one window --------------------------------------------------------

    def _run_window(self):
        """One measurement window; returns
        ``(duration_s, latencies_s, errors, server_delta)``."""
        collector = self.manager.collector
        before = self.backend.stats_snapshot(self.model)
        collector.start_window()
        t0 = time.perf_counter()
        if self.measurement_mode == "time_windows":
            deadline = t0 + self.measurement_interval_s
            while True:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                if (self.early_exit is not None
                        and self.early_exit.is_set()):
                    break
                time.sleep(min(0.05, remaining))
        else:
            # a count window still needs an escape hatch: a wedged
            # server must not hang the profiler forever
            collector.wait_for_completions(
                self.measurement_request_count,
                timeout_s=max(60.0, 100 * self.measurement_interval_s),
                early_exit=self.early_exit)
        duration = time.perf_counter() - t0
        latencies, errors = collector.end_window()
        after = self.backend.stats_snapshot(self.model)
        return duration, latencies, errors, metrics.server_stats_delta(
            before, after)

    # -- one load level ----------------------------------------------------

    def profile_level(self, level):
        """Measure one load level to stability; returns a
        :class:`ProfileResult`.

        ``result.stable`` is False when ``max_trials`` windows never
        converged (a trending system) or the early-exit event fired —
        the partial numbers are still reported, flagged."""
        self.manager.change_level(level)
        if self.warmup_s > 0:
            # event-aware: a first SIGINT mid-warmup must fall through
            # to the (truncated) window and its partial report, not
            # stall out the whole warmup first
            if self.early_exit is not None:
                self.early_exit.wait(self.warmup_s)
            else:
                time.sleep(self.warmup_s)
        detector = StabilityDetector(
            self.stability_pct, self.stability_windows,
            check_latency=self.check_latency_stability)
        router_before = self.backend.router_snapshot()
        windows = []  # (duration, latencies, errors, server_delta)
        stable = False
        interrupted = False
        for trial in range(self.max_trials):
            window = self._run_window()
            duration, latencies, errors, _ = window
            if duration <= 0:
                continue
            windows.append(window)
            avg_lat = (sum(latencies) / len(latencies)
                       if latencies else 0.0)
            detector.add_window(len(latencies) / duration, avg_lat)
            if self.verbose:
                print("  trial {:2d}: {:8.1f} infer/sec, avg {:8.1f} usec"
                      .format(trial + 1, len(latencies) / duration,
                              avg_lat * 1e6), flush=True)
            if self.early_exit is not None and self.early_exit.is_set():
                interrupted = True
                break
            if len(windows) >= self.stability_windows and detector.stable():
                stable = True
                break
        merge_from = windows[-self.stability_windows:]
        merged = metrics.merge_window_records(
            [(w[0], w[1], w[2]) for w in merge_from])
        # server-side deltas sum across the merged windows
        server_delta = {}
        for w in merge_from:
            for key, val in w[3].items():
                server_delta[key] = server_delta.get(key, 0) + val
        breakdown = metrics.server_breakdown(server_delta)
        latency = metrics.latency_summary(merged["latencies_s"])
        result = ProfileResult(
            mode=self.manager.mode,
            level=level,
            stable=stable,
            interrupted=interrupted,
            trials=len(windows),
            throughput=merged["throughput"],
            completed=merged["completed"],
            errors=merged["errors"],
            duration_s=merged["duration_s"],
            server_inference_count=server_delta.get("inference_count", 0),
            server_execution_count=server_delta.get("execution_count", 0),
            client_overhead_pct=metrics.client_overhead_pct(
                latency["avg_usec"], breakdown["server_total_usec"]),
        )
        result.update(latency)
        result.update(breakdown)
        metrics.attach_router_delta(result, router_before,
                                    self.backend.router_snapshot())
        return result

    # -- the sweep ---------------------------------------------------------

    def sweep(self, levels):
        """Linear sweep over load levels (the reference's
        ``--concurrency-range start:end:step`` walk).  Stops early when
        the early-exit event fires; always returns the levels measured
        so far."""
        results = []
        for level in levels:
            if self.early_exit is not None and self.early_exit.is_set():
                break
            results.append(self.profile_level(level))
            if results[-1]["interrupted"]:
                break
        return results


def parse_range(text):
    """``start:end[:step]`` -> list of levels (reference CLI form).
    A bare number means that single level."""
    parts = [int(p) for p in str(text).split(":")]
    if len(parts) == 1:
        return parts
    if len(parts) == 2:
        start, end, step = parts[0], parts[1], 1
    elif len(parts) == 3:
        start, end, step = parts
    else:
        raise ValueError(
            "range must be start:end[:step], got {!r}".format(text))
    if start < 1 or end < start or step < 1:
        raise ValueError(
            "bad range {!r}: need 1 <= start <= end, step >= 1".format(
                text))
    return list(range(start, end + 1, step))
