"""Distributed multi-process perfanalyzer coordination.

Python port of the reference's optional MPI driver (``MPIDriver``,
mpi_utils.h:32-83, used at perf_analyzer.cc:353-368): one parent
coordinator forks N perf_analyzer *worker processes* — each pinned to
a replica, or round-robined through a fleet router — and runs
**barrier-synchronized measurement windows** over a localhost socket
control channel (the ``MPI_Barrier``-around-``Profile`` analog with
no dlopen'd libmpi).  One process can saturate neither a fleet nor
its own GIL; N processes measuring the SAME wall-clock window can,
and their merged report is the proof-at-scale number the single
process cannot produce.

Protocol (newline-delimited JSON over one TCP connection per worker):

    worker -> parent   {"type": "hello", "worker": i}
    parent -> workers  {"type": "start_window", "window": k,
                        "duration_s": w}          # the barrier release
    worker -> parent   {"type": "window_result", "window": k,
                        "completed": n, "errors": e, "duration_s": d,
                        "latencies_s": [...], "tokens": t}
                       # tokens: 0 from scalar workers; generation
                       # workers fill it and add ttfts_s / itls_s /
                       # generations / resumed_streams / resume_events
    parent -> workers  {"type": "shutdown"}

The parent broadcasts ``start_window`` only after every worker's
previous ``window_result`` arrived — that gather+broadcast IS the
barrier, so every worker's window k covers the same wall-clock span.
Workers keep their load loops running *between* windows (the fleet
stays saturated; windows gate measurement, not load) — the same
window-gating the single-process profiler's collector does.

Merging is the part the reference is adamant about and so are we:
**merge raw samples, never average percentiles**
(:func:`merge_worker_windows` concatenates every worker's raw latency
records before computing p50/p90/p95/p99), and fleet throughput is
the *sum of worker completions* over the synchronized window — both
unit-pinned against a single-process computation on identical
synthetic latencies in tests/test_coordinator.py.

``tools/perf_analyzer.py --workers N`` is the CLI front door; the
tier-1 tests drive it against ``tests/fleet_stub.py`` stub replicas
so no jax import or llama compile rides the gate.
"""

import json
import socket
import time

from perfanalyzer import metrics

__all__ = [
    "Coordinator",
    "WorkerChannel",
    "merge_worker_windows",
    "merge_windows",
    "reap_workers",
]


def _send_json(sock, obj):
    sock.sendall((json.dumps(obj) + "\n").encode("utf-8"))


class _LineReader:
    """Newline-delimited JSON reader over one socket."""

    def __init__(self, sock):
        self._sock = sock
        self._buf = b""

    def recv(self, timeout_s):
        self._sock.settimeout(timeout_s)
        while b"\n" not in self._buf:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("control channel closed")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\n", 1)
        return json.loads(line)


# -- merge math (pure, clock-free — the unit-pinned part) -------------------


def merge_worker_windows(worker_results):
    """Merge one synchronized window's per-worker results into the
    fleet-level window row.

    ``worker_results`` is a list of dicts carrying ``completed``,
    ``errors``, ``duration_s``, ``latencies_s`` (raw per-request
    seconds) and optionally ``tokens``.  Fleet throughput is the SUM
    of worker completions over the synchronized window span (the
    longest worker duration — the barrier released them together, so
    the spans coincide up to scheduling jitter); latency percentiles
    come from the POOLED raw samples, never from averaging per-worker
    percentiles (reference MergePerfStatusReports semantics)."""
    latencies = [lat for r in worker_results
                 for lat in r.get("latencies_s", [])]
    completed = sum(int(r.get("completed", 0)) for r in worker_results)
    errors = sum(int(r.get("errors", 0)) for r in worker_results)
    tokens = sum(int(r.get("tokens", 0)) for r in worker_results)
    duration = max(
        (float(r.get("duration_s", 0.0)) for r in worker_results),
        default=0.0)
    row = {
        "workers": len(worker_results),
        "completed": completed,
        "errors": errors,
        "tokens": tokens,
        "duration_s": duration,
        "throughput": completed / duration if duration > 0 else 0.0,
        "latencies_s": latencies,
    }
    row.update(metrics.latency_summary(latencies))
    # generation-mode workers additionally ship raw TTFT/ITL samples
    # and stream counters; pool/sum them under the same raw-samples
    # rule so the parent can compute fleet token percentiles
    if any("ttfts_s" in r or "generations" in r for r in worker_results):
        row["ttfts_s"] = [t for r in worker_results
                          for t in r.get("ttfts_s", [])]
        row["itls_s"] = [t for r in worker_results
                         for t in r.get("itls_s", [])]
        for key in ("generations", "resumed_streams", "resume_events"):
            row[key] = sum(int(r.get(key, 0)) for r in worker_results)
    return row


def merge_windows(window_rows):
    """Collapse the per-window merged rows into ONE report sample:
    total completions over total duration, percentiles over every raw
    record of every window (same math as the single-process
    profiler's 3-window merge, across the whole run)."""
    latencies = [lat for w in window_rows
                 for lat in w.get("latencies_s", [])]
    duration = sum(w.get("duration_s", 0.0) for w in window_rows)
    completed = sum(w.get("completed", 0) for w in window_rows)
    merged = {
        "completed": completed,
        "errors": sum(w.get("errors", 0) for w in window_rows),
        "tokens": sum(w.get("tokens", 0) for w in window_rows),
        "duration_s": duration,
        "throughput": completed / duration if duration > 0 else 0.0,
        "windows": len(window_rows),
    }
    merged.update(metrics.latency_summary(latencies))
    if any("ttfts_s" in w or "generations" in w for w in window_rows):
        merged["ttfts_s"] = [t for w in window_rows
                             for t in w.get("ttfts_s", [])]
        merged["itls_s"] = [t for w in window_rows
                            for t in w.get("itls_s", [])]
        for key in ("generations", "resumed_streams", "resume_events"):
            merged[key] = sum(int(w.get(key, 0)) for w in window_rows)
    return merged


# -- the parent --------------------------------------------------------------


class Coordinator:
    """The parent side: listen, admit N workers, drive the barrier.

    Use as::

        coord = Coordinator(workers=2).listen()
        procs = [spawn(argv + ["--worker-connect", coord.address,
                               "--worker-id", str(i)]) ...]
        coord.wait_for_workers(timeout_s=60)
        window_rows = coord.run_windows(windows=3, window_s=2.0)
        coord.shutdown()

    Every worker failure surfaces as a raised ``RuntimeError`` naming
    the worker — a silent partial fleet would report numbers that look
    like the whole fleet's.
    """

    def __init__(self, workers, host="127.0.0.1", port=0,
                 result_timeout_s=120.0):
        if workers < 1:
            raise ValueError("need at least one worker")
        self._want = int(workers)
        self._result_timeout_s = float(result_timeout_s)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(
            socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._conns = []    # (worker_id, sock, reader), hello order
        self._listening = False

    def listen(self):
        self._listener.listen(self._want)
        self._listening = True
        return self

    @property
    def address(self):
        host, port = self._listener.getsockname()
        return "{}:{}".format(host, port)

    def wait_for_workers(self, timeout_s=60.0):
        """Accept connections until every worker said hello."""
        if not self._listening:
            self.listen()
        deadline = time.monotonic() + float(timeout_s)
        while len(self._conns) < self._want:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise RuntimeError(
                    "only {}/{} workers connected within {}s".format(
                        len(self._conns), self._want, timeout_s))
            self._listener.settimeout(remaining)
            try:
                sock, _addr = self._listener.accept()
            except socket.timeout:
                continue
            reader = _LineReader(sock)
            hello = reader.recv(min(10.0, remaining))
            if hello.get("type") != "hello":
                sock.close()
                raise RuntimeError(
                    "worker handshake sent {!r}, not hello".format(hello))
            self._conns.append((int(hello.get("worker", -1)), sock, reader))

    def _broadcast(self, obj):
        for _wid, sock, _reader in self._conns:
            _send_json(sock, obj)

    def run_window(self, index, window_s):
        """One barrier-synchronized window: broadcast the release,
        gather every worker's result, merge.  The broadcast happens
        only once the previous gather completed, so all N windows
        cover the same wall-clock span."""
        self._broadcast({"type": "start_window", "window": index,
                         "duration_s": window_s})
        results = []
        for wid, _sock, reader in self._conns:
            try:
                msg = reader.recv(self._result_timeout_s + window_s)
            except (ConnectionError, socket.timeout, OSError) as e:
                raise RuntimeError(
                    "worker {} died mid-window {}: {}".format(
                        wid, index, e))
            if msg.get("type") != "window_result" or \
                    msg.get("window") != index:
                raise RuntimeError(
                    "worker {} answered window {} with {!r}".format(
                        wid, index, msg))
            results.append(msg)
        return merge_worker_windows(results)

    def run_windows(self, windows, window_s):
        return [self.run_window(i, window_s) for i in range(windows)]

    def shutdown(self):
        try:
            self._broadcast({"type": "shutdown"})
        except OSError:
            pass
        for _wid, sock, _reader in self._conns:
            try:
                sock.close()
            except OSError:
                pass
        self._conns = []
        self._listener.close()


# -- the worker --------------------------------------------------------------


class WorkerChannel:
    """The worker side of the control channel: connect, say hello,
    then serve barrier windows until shutdown.

    ``run_window_fn(duration_s, index)`` must return the window-result
    payload fields (``completed``/``errors``/``duration_s``/
    ``latencies_s``/optionally ``tokens``); this class owns only the
    framing.
    """

    def __init__(self, address, worker_id, connect_timeout_s=30.0):
        host, sep, port = address.rpartition(":")
        if not sep:
            raise ValueError(
                "coordinator address must be host:port (got {!r})"
                .format(address))
        self.worker_id = int(worker_id)
        self._sock = socket.create_connection(
            (host, int(port)), timeout=connect_timeout_s)
        self._reader = _LineReader(self._sock)
        _send_json(self._sock, {"type": "hello", "worker": self.worker_id})

    def serve(self, run_window_fn, idle_timeout_s=600.0):
        """Window loop; returns the number of windows served."""
        served = 0
        while True:
            msg = self._reader.recv(idle_timeout_s)
            kind = msg.get("type")
            if kind == "shutdown":
                return served
            if kind != "start_window":
                raise RuntimeError(
                    "unexpected control message {!r}".format(msg))
            index = int(msg.get("window", served))
            result = run_window_fn(
                float(msg.get("duration_s", 1.0)), index)
            payload = {"type": "window_result", "window": index}
            payload.update(result)
            _send_json(self._sock, payload)
            served += 1

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


# -- worker-process plumbing -------------------------------------------------


def reap_workers(procs, timeout_s=30.0):
    """Join every worker process; kill stragglers past the deadline.
    Returns the list of exit codes."""
    import subprocess

    deadline = time.monotonic() + float(timeout_s)
    codes = []
    for proc in procs:
        remaining = max(0.0, deadline - time.monotonic())
        try:
            codes.append(proc.wait(timeout=remaining))
        except subprocess.TimeoutExpired:
            proc.kill()
            try:
                codes.append(proc.wait(timeout=5))
            except subprocess.TimeoutExpired:
                codes.append(None)
    return codes
