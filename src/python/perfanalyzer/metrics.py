"""Pure measurement math: percentiles, window summaries, and
client/server statistic merging.

Role of the reference's ``PerfStatus`` / ``ClientSideStats`` /
``ServerSideStats`` plumbing (inference_profiler.h:97-162,
MergePerfStatusReports at inference_profiler.cc:948).  Everything here
is deterministic and clock-free so the unit tests can drive it with
synthetic numbers.
"""


def percentile(values, pct, presorted=False):
    """Linear-interpolated percentile of ``values`` (``pct`` in 0..100).

    Matches numpy's default ('linear') method so client-side latency
    percentiles agree with any offline re-analysis of the raw records.
    ``presorted=True`` skips the sort — callers that already hold a
    sorted sample (latency summaries over tens of thousands of window
    records) pay for one sort, not one per percentile.
    """
    if not values:
        raise ValueError("percentile of an empty sample")
    if not 0 <= pct <= 100:
        raise ValueError(
            "percentile must be in [0, 100], got {}".format(pct))
    ordered = values if presorted else sorted(values)
    if len(ordered) == 1:
        return ordered[0]
    rank = (pct / 100.0) * (len(ordered) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(ordered) - 1)
    frac = rank - lo
    return ordered[lo] * (1.0 - frac) + ordered[hi] * frac


#: 99.9 rides along for tail-latency work (the "Tail at Scale" metric
#: the router's gray-failure ejection defends): percentiles are always
#: computed over POOLED raw samples — never an average of per-window
#: percentiles, which has no statistical meaning (reference
#: MergePerfStatusReports semantics, pinned against numpy in
#: tests/test_perfanalyzer.py).
LATENCY_PERCENTILES = (50, 90, 95, 99, 99.9)


def _pct_key(p):
    """``p99_usec`` / ``p99.9_usec``: integral percentiles render
    without the float's trailing ``.0``."""
    return "p{:g}_usec".format(p)


def latency_summary(latencies_s):
    """p50/p90/p95/p99/p99.9 + avg/min/max of a latency sample, in
    microseconds (the unit every report row carries)."""
    if not latencies_s:
        return {"avg_usec": None, "min_usec": None, "max_usec": None,
                **{_pct_key(p): None for p in LATENCY_PERCENTILES}}
    usec = sorted(v * 1e6 for v in latencies_s)
    out = {
        "avg_usec": sum(usec) / len(usec),
        "min_usec": usec[0],
        "max_usec": usec[-1],
    }
    for p in LATENCY_PERCENTILES:
        out[_pct_key(p)] = percentile(usec, p, presorted=True)
    return out


# -- server-side statistics ------------------------------------------------

_DURATION_KEYS = ("success", "fail", "queue", "compute_input",
                  "compute_infer", "compute_output")


def server_stats_snapshot(stats, model_name):
    """Normalize one model's cumulative stats out of a
    ``get_inference_statistics()`` result.

    Accepts the HTTP client's plain-JSON dict and the gRPC client's
    ``as_json=True`` form alike (proto int64s arrive as *strings* after
    MessageToDict; everything is coerced to int here).  Returns a flat
    dict: ``inference_count``, ``execution_count``, and
    ``<bucket>_count`` / ``<bucket>_ns`` for each duration bucket.
    """
    for entry in stats.get("model_stats", []):
        if entry.get("name") == model_name:
            infer_stats = entry.get("inference_stats", {})
            snap = {
                "inference_count": int(entry.get("inference_count", 0)),
                "execution_count": int(entry.get("execution_count", 0)),
            }
            for key in _DURATION_KEYS:
                bucket = infer_stats.get(key, {})
                snap[key + "_count"] = int(bucket.get("count", 0))
                snap[key + "_ns"] = int(bucket.get("ns", 0))
            return snap
    raise KeyError(
        "model '{}' not present in server statistics".format(model_name))


def zero_snapshot():
    """An all-zero flat snapshot (the delta identity)."""
    snap = {"inference_count": 0, "execution_count": 0}
    for key in _DURATION_KEYS:
        snap[key + "_count"] = 0
        snap[key + "_ns"] = 0
    return snap


def server_stats_delta(before, after):
    """Per-bucket deltas between two snapshots (one measurement window's
    worth of server-side work).  Counters are cumulative on the server,
    so the diff isolates exactly the window — the profiler reads queue
    vs compute time for the requests IT sent, not the server's
    lifetime.

    Multi-replica snapshots carry a ``_replicas`` map (replica key ->
    flat snapshot); those are diffed PER REPLICA and only for replicas
    present in both snapshots — a replica that died or (re)appeared
    mid-window would otherwise subtract or add its whole lifetime's
    counters into one window's delta."""
    reps_before = before.get("_replicas")
    reps_after = after.get("_replicas")
    if reps_before is not None and reps_after is not None:
        total = zero_snapshot()
        for key in reps_after:
            if key not in reps_before:
                continue
            for field in total:
                total[field] += (reps_after[key][field]
                                 - reps_before[key][field])
        return total
    return {key: after[key] - before[key]
            for key in after if key != "_replicas"}


def server_breakdown(delta):
    """Per-request server-side microsecond breakdown + the fractions the
    overhead report prints.

    Returns ``queue_usec`` / ``compute_infer_usec`` (+input/output) per
    successful request, and ``server_total_usec`` (their sum) — the
    time the server itself accounts for.  The client-overhead
    percentage is computed against the measured client latency by
    :func:`client_overhead_pct`."""
    n = max(1, delta.get("success_count", 0))
    out = {}
    total = 0.0
    for key in ("queue", "compute_input", "compute_infer",
                "compute_output"):
        usec = delta.get(key + "_ns", 0) / 1e3 / n
        out[key + "_usec"] = usec
        total += usec
    out["server_total_usec"] = total
    return out


def client_overhead_pct(client_avg_usec, server_total_usec):
    """Share of the client-observed latency NOT accounted for by the
    server's own buckets: transport, (de)serialization, client stack.
    Clamped to [0, 100] — clock skew between the two sides can
    otherwise push it slightly negative."""
    if not client_avg_usec or client_avg_usec <= 0:
        return None
    pct = 100.0 * (1.0 - server_total_usec / client_avg_usec)
    return min(100.0, max(0.0, pct))


def merge_window_records(windows):
    """Merge per-window request records into one report sample.

    ``windows`` is a list of (duration_s, [latency_s, ...], error_count)
    tuples — the stability run's last three windows.  Throughput is
    total completions over total duration (NOT the mean of per-window
    rates: windows may differ slightly in length, and requests are the
    natural weight); the latency sample is pooled so percentiles rest
    on every record (reference MergePerfStatusReports semantics).
    """
    total_s = sum(w[0] for w in windows)
    latencies = [lat for w in windows for lat in w[1]]
    errors = sum(w[2] for w in windows)
    throughput = len(latencies) / total_s if total_s > 0 else 0.0
    return {
        "throughput": throughput,
        "latencies_s": latencies,
        "completed": len(latencies),
        "errors": errors,
        "duration_s": total_s,
    }


#: Fleet-supervisor counters relayed through ``/router/stats`` when a
#: supervisor is attached — the process-level twin of the router's own
#: failover/handoff counters.
SUPERVISOR_COUNTERS = (
    "supervisor_replica_restarts",
    "supervisor_scale_up_events",
    "supervisor_scale_down_events",
    "supervisor_retired_replicas",
    # crash durability (ISSUE 18): a nonzero per-window adoption delta
    # means the SUPERVISOR itself restarted under this level and the
    # fleet kept serving through it
    "supervisor_adoptions",
    "supervisor_clean_handovers",
    "supervisor_stale_children_reaped",
    "supervisor_manifest_records",
)


def attach_router_delta(result, before, after):
    """Fold a load level's fleet-router counter deltas into a
    :class:`~perfanalyzer.profiler.ProfileResult` as ``router_*``
    fields.

    Only set when the backend target IS a router (both snapshots
    non-None; see ``ClientBackend.router_snapshot``).  Level-scoped on
    purpose — a router absorbs faults *between* the client and the
    fleet, so its failover/handoff counters are the server-side twin of
    the client-side ``resumed_streams``: nonzero means replicas were
    dying or shedding under this level even though every request still
    succeeded.

    When the router fronts a supervised fleet (``tpuserver.fleet``) the
    snapshot also carries the supervisor's process-level healing
    counters (``supervisor_replica_restarts`` etc.); those diff the
    same way — a nonzero per-window delta means whole replica
    PROCESSES died, scaled, or retired under this level."""
    if before is None or after is None:
        return
    for key in ("failovers", "handoffs", "resumed_streams", "shed"):
        result["router_" + key] = after[key] - before[key]
    # tail-latency defense (gray-failure soft-ejections, hedge fires)
    # and router-HA (standby takeovers, journal-recovered generations)
    # counters diff the same way — guarded presence-in-both like the
    # supervisor counters so a snapshot from a router predating them
    # never fabricates a delta.  A nonzero takeover delta means the
    # FRONT TIER failed over under this level and every request still
    # in the window rode it out.
    for key in ("ejections", "hedges", "takeovers",
                "recovered_generations"):
        if key in before and key in after:
            result["router_" + key] = after[key] - before[key]
    for key in SUPERVISOR_COUNTERS:
        if key in before and key in after:
            result[key] = after[key] - before[key]
    # disaggregated prefill/decode: the phase-split orchestrator's
    # counters ride the snapshot as a nested dict.  Diff the cumulative
    # members and derive the per-phase averages the generation report
    # renders (prefill-queue ms per split, KV-transfer ms per
    # transfer) — all presence-guarded, so a router predating the
    # split plane never fabricates a column.
    disagg_before, disagg_after = before.get("disagg"), after.get("disagg")
    if isinstance(disagg_before, dict) and isinstance(disagg_after, dict):
        for key in ("splits", "transfers", "transfer_bytes",
                    "transfer_ms_total", "prefill_queue_ms_total"):
            if key in disagg_before and key in disagg_after:
                result["disagg_" + key] = (
                    disagg_after[key] - disagg_before[key])
        result["disagg_fallbacks"] = (
            sum((disagg_after.get("fallbacks") or {}).values())
            - sum((disagg_before.get("fallbacks") or {}).values()))
        splits = result.get("disagg_splits")
        if splits:
            result["prefill_queue_ms"] = (
                result["disagg_prefill_queue_ms_total"] / splits)
        transfers = result.get("disagg_transfers")
        if transfers:
            result["kv_transfer_ms"] = (
                result["disagg_transfer_ms_total"] / transfers)
