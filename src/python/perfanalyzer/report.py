"""Report writer: stdout table, CSV, and BENCH-schema JSON rows.

Role of the reference's ``ReportWriter`` (report_writer.cc): one
measurement per load level in, three renderings out.  The JSON rows
use the same one-line-per-measurement schema as the repo's
``BENCH_*.json`` trajectory (``config``/``metric``/``value``/``unit``/
``vs_baseline`` + extras), so perf_analyzer output can land next to
the existing bench history unmodified.
"""

import csv
import json


_SCALAR_COLUMNS = [
    ("level", "{:d}"),
    ("throughput", "{:.1f}"),
    ("avg_usec", "{:.1f}"),
    ("p50_usec", "{:.1f}"),
    ("p90_usec", "{:.1f}"),
    ("p95_usec", "{:.1f}"),
    ("p99_usec", "{:.1f}"),
    ("p99.9_usec", "{:.1f}"),
    ("queue_usec", "{:.1f}"),
    ("compute_infer_usec", "{:.1f}"),
    ("client_overhead_pct", "{:.1f}"),
    ("errors", "{:d}"),
    ("stable", "{}"),
]

_SCALAR_HEADERS = [
    "Level", "infer/sec", "avg(us)", "p50(us)", "p90(us)", "p95(us)",
    "p99(us)", "p99.9(us)", "queue(us)", "compute(us)", "overhead%",
    "errors", "stable",
]

_GEN_COLUMNS = [
    ("level", "{:d}"),
    ("throughput", "{:.1f}"),
    ("gen_per_sec", "{:.2f}"),
    ("ttft_avg_ms", "{:.1f}"),
    ("ttft_p50_ms", "{:.1f}"),
    ("ttft_p99_ms", "{:.1f}"),
    ("itl_p50_ms", "{:.2f}"),
    ("itl_p90_ms", "{:.2f}"),
    ("itl_p99_ms", "{:.2f}"),
    ("prefix_hit_pct", "{:.1f}"),
    # speculative decoding (window-delta'd like the prefix-cache
    # column): mean tokens per speculative step and the draft
    # acceptance rate; absent on pre-speculation targets
    ("spec_accept_per_step", "{:.2f}"),
    ("spec_hit_pct", "{:.1f}"),
    # per-phase columns from the router's disagg counters (set by
    # attach_router_delta only when the target router runs the
    # phase-split plane; absent fields render "-", never 0)
    ("prefill_queue_ms", "{:.2f}"),
    ("kv_transfer_ms", "{:.2f}"),
    ("errors", "{:d}"),
    ("stable", "{}"),
]

_GEN_HEADERS = [
    "Streams", "tokens/sec", "gen/sec", "TTFT avg(ms)", "TTFT p50(ms)",
    "TTFT p99(ms)", "ITL p50(ms)", "ITL p90(ms)", "ITL p99(ms)",
    "prefix-hit%", "accept/step", "spec-hit%",
    "prefill-q(ms)", "kv-xfer(ms)", "errors", "stable",
]

#: Per-window CSV schema: the reference ReportWriter's columns
#: (``Concurrency,Inferences/Second,Client Send,Network+Server
#: Send/Recv,Server Queue,Server Compute Input,Server Compute Infer,
#: Server Compute Output,Client Recv,p50/p90/p95/p99 latency`` —
#: report_writer.cc:73-260, SURVEY §6) plus this stack's generation
#: columns (TTFT/ITL/tokens-per-sec).  One row per measurement
#: window; absent fields render empty, never zero (a 0 is a
#: measurement, an empty cell is "not measured").
WINDOW_CSV_COLUMNS = [
    ("Concurrency", "concurrency"),
    ("Inferences/Second", "throughput"),
    ("Client Send", "client_send_usec"),
    ("Network+Server Send/Recv", "network_usec"),
    ("Server Queue", "queue_usec"),
    ("Server Compute Input", "compute_input_usec"),
    ("Server Compute Infer", "compute_infer_usec"),
    ("Server Compute Output", "compute_output_usec"),
    ("Client Recv", "client_recv_usec"),
    ("p50 latency", "p50_usec"),
    ("p90 latency", "p90_usec"),
    ("p95 latency", "p95_usec"),
    ("p99 latency", "p99_usec"),
    ("p99.9 latency", "p99.9_usec"),
    ("TTFT avg ms", "ttft_avg_ms"),
    ("ITL p50 ms", "itl_p50_ms"),
    ("Tokens/Second", "tokens_per_sec"),
]


def _fmt(value, fmt):
    if value is None:
        return "-"
    try:
        return fmt.format(value)
    except (TypeError, ValueError):
        return str(value)


class ReportWriter:
    """Render a sweep's :class:`ProfileResult` rows."""

    def __init__(self, model, backend_kind, extra_tags=None):
        self.model = model
        self.backend_kind = backend_kind
        self.extra_tags = dict(extra_tags or {})

    @staticmethod
    def _is_generation(results):
        # covers generation_concurrency AND distributed_generation
        return bool(results) and "generation" in results[0].get("mode", "")

    def table(self, results):
        """The stdout table, as a string."""
        if not results:
            return "(no measurements)"
        columns = (_GEN_COLUMNS if self._is_generation(results)
                   else _SCALAR_COLUMNS)
        headers = (_GEN_HEADERS if self._is_generation(results)
                   else _SCALAR_HEADERS)
        rows = [
            [_fmt(r.get(key), fmt) for key, fmt in columns]
            for r in results
        ]
        widths = [
            max(len(h), max((len(row[i]) for row in rows), default=0))
            for i, h in enumerate(headers)
        ]
        lines = [
            "  ".join(h.rjust(w) for h, w in zip(headers, widths)),
            "  ".join("-" * w for w in widths),
        ]
        for row in rows:
            lines.append(
                "  ".join(c.rjust(w) for c, w in zip(row, widths)))
        return "\n".join(lines)

    def print_table(self, results, file=None):
        mode = results[0]["mode"] if results else "?"
        print("\n*** {} | model={} backend={} mode={} ***".format(
            "perf_analyzer", self.model, self.backend_kind, mode),
            file=file)
        print(self.table(results), file=file, flush=True)
        if any(r.get("router_handoffs") is not None for r in results):
            # the target is a fleet router: its per-level resilience
            # counters sit next to the client-side resumed_streams —
            # nonzero means replicas died or shed under this level even
            # when every request above still succeeded
            for r in results:
                if r.get("router_handoffs") is None:
                    continue  # this level's snapshot transiently failed
                line = ("  level {}: router failovers={} handoffs={} "
                        "resumed_streams={} shed={}".format(
                            r.get("level"),
                            r.get("router_failovers"),
                            r.get("router_handoffs"),
                            r.get("router_resumed_streams"),
                            r.get("router_shed")))
                if r.get("router_ejections") is not None:
                    # tail-latency defense: gray-failure soft-ejections
                    # and hedge fires under this level — nonzero
                    # ejections with flat errors means the router
                    # routed around a slow replica without the client
                    # noticing
                    line += " ejections={} hedges={}".format(
                        r.get("router_ejections"),
                        r.get("router_hedges"))
                if r.get("router_takeovers") is not None:
                    # router HA: a nonzero takeover delta means the
                    # FRONT TIER itself failed over (standby promoted)
                    # under this level; recovered counts the
                    # generations the journal rebuilt for resumes
                    line += " takeovers={} recovered={}".format(
                        r.get("router_takeovers"),
                        r.get("router_recovered_generations"))
                if r.get("supervisor_replica_restarts") is not None:
                    # a supervised fleet sits behind the router: its
                    # per-window process-healing counters ride along —
                    # nonzero means whole replica processes died or
                    # scaled under this level
                    line += (" | supervisor restarts={} scale_up={} "
                             "scale_down={} retired={}".format(
                                 r.get("supervisor_replica_restarts"),
                                 r.get("supervisor_scale_up_events"),
                                 r.get("supervisor_scale_down_events"),
                                 r.get("supervisor_retired_replicas")))
                if r.get("supervisor_adoptions") is not None:
                    # crash durability: a nonzero adoption delta means
                    # the SUPERVISOR itself restarted under this level
                    # and adopted its children instead of respawning
                    # them — serving never flinched
                    line += " adoptions={}".format(
                        r.get("supervisor_adoptions"))
                print(line, file=file, flush=True)

    def write_csv(self, path, results):
        """Reference-style CSV: one row per load level."""
        if not results:
            return
        columns = (_GEN_COLUMNS if self._is_generation(results)
                   else _SCALAR_COLUMNS)
        with open(path, "w", newline="") as fh:
            writer = csv.writer(fh)
            writer.writerow([key for key, _ in columns])
            for r in results:
                writer.writerow([r.get(key) for key, _ in columns])

    def write_window_csv(self, path, windows):
        """Per-window CSV (``--report-csv``): one row per synchronized
        measurement window in the reference schema
        (:data:`WINDOW_CSV_COLUMNS`).  ``windows`` is the list of
        merged window rows the distributed coordinator produces —
        round-trip pinned (parse back, row count == windows) in
        tests/test_coordinator.py."""
        with open(path, "w", newline="") as fh:
            writer = csv.writer(fh)
            writer.writerow([header for header, _ in WINDOW_CSV_COLUMNS])
            for w in windows:
                writer.writerow([
                    "" if w.get(key) is None else w.get(key)
                    for _, key in WINDOW_CSV_COLUMNS])

    def json_rows(self, results):
        """BENCH-schema dicts, one per load level."""
        rows = []
        generation = self._is_generation(results)
        for r in results:
            row = {
                "config": "perf_analyzer",
                "metric": "{}_{}_{}{}".format(
                    self.model, self.backend_kind,
                    "gen_streams" if generation else r.get(
                        "mode", "level"),
                    r.get("level")),
                "value": round(r.get("throughput") or 0.0, 2),
                "unit": "tokens/sec" if generation else "infer/sec",
                "vs_baseline": None,
                "mode": r.get("mode"),
                "level": r.get("level"),
                "stable": bool(r.get("stable")),
            }
            for key, val in r.items():
                if key in ("mode", "level", "throughput", "stable"):
                    continue
                if isinstance(val, float):
                    row[key] = round(val, 3)
                elif isinstance(val, (int, bool, str)) or val is None:
                    row[key] = val
            row.update(self.extra_tags)
            rows.append(row)
        return rows

    def print_json(self, results, file=None):
        for row in self.json_rows(results):
            print(json.dumps(row), file=file, flush=True)

    def write_json(self, path, results):
        with open(path, "w") as fh:
            for row in self.json_rows(results):
                fh.write(json.dumps(row) + "\n")
