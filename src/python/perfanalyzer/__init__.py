"""perfanalyzer: load-generation & profiling harness for the serving stack.

Python port of the reference `perf_analyzer` (SURVEY.md §2.2, §3.4)
shaped for this repo: pluggable client backends (triton-HTTP,
triton-gRPC, in-process core, multi-replica pool), concurrency and
request-rate load managers, a measurement-window profiler with
3-consecutive-window stability detection and client/server stat
merging, a generation mode reporting token-level metrics (TTFT, ITL,
tokens/sec), and a report writer (stdout table / CSV / JSON rows).

Entry points:

- :func:`perfanalyzer.client_backend.create_backend` — backend factory
- :class:`perfanalyzer.load_manager.ConcurrencyManager` /
  :class:`~perfanalyzer.load_manager.RequestRateManager` — load managers
- :class:`perfanalyzer.profiler.InferenceProfiler` — windowed profiler
- :class:`perfanalyzer.generation.GenerationProfiler` — token metrics
- :class:`perfanalyzer.report.ReportWriter` — table / CSV / JSON output
- ``tools/perf_analyzer.py`` — the CLI that wires them together
"""

from perfanalyzer.client_backend import ClientBackend, create_backend
from perfanalyzer.generation import GenerationProfiler
from perfanalyzer.load_manager import (
    ConcurrencyManager,
    LoadCollector,
    RequestRateManager,
)
from perfanalyzer.metrics import (
    latency_summary,
    merge_window_records,
    percentile,
    server_stats_delta,
    server_stats_snapshot,
)
from perfanalyzer.profiler import InferenceProfiler
from perfanalyzer.report import ReportWriter
from perfanalyzer.schedule import schedule_distribution
from perfanalyzer.stability import StabilityDetector

__all__ = [
    "ClientBackend",
    "ConcurrencyManager",
    "GenerationProfiler",
    "InferenceProfiler",
    "LoadCollector",
    "RequestRateManager",
    "ReportWriter",
    "StabilityDetector",
    "create_backend",
    "latency_summary",
    "merge_window_records",
    "percentile",
    "schedule_distribution",
    "server_stats_delta",
    "server_stats_snapshot",
]
