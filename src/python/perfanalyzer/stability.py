"""3-consecutive-window stability detection.

Role of the reference's ``DetermineStability``
(inference_profiler.cc:780-833): a load level's measurement is accepted
only once the last three windows agree on BOTH throughput and average
latency within the stability percentage — so a trending system (still
warming up, compiling, or saturating a queue) keeps measuring instead
of reporting a transient.
"""

from collections import deque


class StabilityDetector:
    """Sliding window over (throughput, latency) measurements.

    ``stability_pct`` is the reference's ``--stability-percentage``
    (default 10): a metric is stable when every one of the last
    ``window_count`` values lies within ±pct of their mean.  Both
    metrics must be stable simultaneously; latency may be exempted
    (``check_latency=False``) the way the reference exempts it under
    request-rate mode's open-loop latencies.
    """

    def __init__(self, stability_pct=10.0, window_count=3,
                 check_latency=True):
        if window_count < 2:
            raise ValueError(
                "stability needs at least 2 windows (got {})".format(
                    window_count))
        self.stability_pct = float(stability_pct)
        self.window_count = int(window_count)
        self.check_latency = bool(check_latency)
        self._windows = deque(maxlen=self.window_count)

    def add_window(self, throughput, avg_latency):
        self._windows.append((float(throughput), float(avg_latency)))

    def reset(self):
        self._windows.clear()

    def _metric_stable(self, values):
        mean = sum(values) / len(values)
        if mean <= 0:
            # a zero-throughput (or zero-latency) plateau is vacuously
            # flat, but it means nothing completed — never "stable"
            return False
        slack = self.stability_pct / 100.0
        return all(abs(v - mean) <= slack * mean for v in values)

    def stable(self):
        """True once ``window_count`` windows agree within the slack."""
        if len(self._windows) < self.window_count:
            return False
        if not self._metric_stable([w[0] for w in self._windows]):
            return False
        if self.check_latency and not self._metric_stable(
                [w[1] for w in self._windows]):
            return False
        return True

    def windows(self):
        return list(self._windows)
