"""Backend-neutral client abstraction for the load managers.

Role of the reference's ``client_backend/`` layer
(client_backend.h:250-620): the load managers and profiler speak one
small interface; four concrete backends map it onto the stack's real
entry points:

- ``http``      — ``tritonclient.http`` against a live HTTP frontend
- ``grpc``      — ``tritonclient.grpc`` against a live gRPC frontend
- ``inprocess`` — wraps ``tpuserver.core.InferenceServer`` directly
                  (the analogue of the reference's Triton C-API
                  backend: no sockets, so the client/transport overhead
                  is isolated from the model cost)
- ``pool``      — drives ``tritonclient.EndpointPool`` over N replica
                  URLs, so failover/hedging behavior can be load-tested

A backend hands out *prepared* requests (inputs pre-serialized once,
outside the timed path), executes them synchronously (``infer``) or
asynchronously (``submit`` + completion callback — what the
concurrency manager's context free-list rides on), snapshots server
statistics, and — where the transport supports decoupled models —
streams generations token-by-token for the generation profiler.
"""

import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np


class BackendError(Exception):
    """A request failed inside a backend (wraps the transport error)."""


def _coerce_int(value, default=0):
    try:
        return int(value)
    except (TypeError, ValueError):
        return default


class ClientBackend:
    """The interface the load managers and profiler consume.

    ``capacity`` is the backend's true in-flight ceiling (executor
    threads / pooled connections), or None when the transport
    multiplexes without a fixed bound (gRPC async).  Size it via
    ``max_inflight`` at construction: a load level above the capacity
    would silently measure the backend's own queueing, not the server.
    """

    kind = "?"
    supports_generation = False

    def __init__(self, max_inflight=None):
        self._executor = None
        self._executor_lock = threading.Lock()
        # an explicit bound is honored EXACTLY (a user capping
        # outstanding requests means it); only the unspecified case
        # gets the roomy default
        self._executor_workers = (max(1, int(max_inflight))
                                  if max_inflight else 64)
        self.capacity = self._executor_workers

    # -- metadata / statistics --------------------------------------------

    def model_metadata(self, model):
        raise NotImplementedError

    def model_config(self, model):
        raise NotImplementedError

    def server_statistics(self, model):
        """Cumulative stats dict ``{"model_stats": [...]}`` (KServe
        statistics extension shape, both clients' native form)."""
        raise NotImplementedError

    def stats_snapshot(self, model):
        """Flat cumulative-counter snapshot for the profiler's window
        diffs (see :func:`perfanalyzer.metrics.server_stats_snapshot`).
        Multi-replica backends override to attach per-replica data so
        deltas can be paired replica-by-replica."""
        from perfanalyzer.metrics import server_stats_snapshot

        return server_stats_snapshot(self.server_statistics(model), model)

    def router_snapshot(self):
        """Cumulative fleet-router counters (``failovers``,
        ``handoffs``, ``resumed_streams``, ``shed``) when the target is
        a ``tpuserver.router.FleetRouter``, else None.  Only transports
        that can reach ``/router/stats`` override this — the profiler
        diffs the snapshot per load level so router-absorbed faults
        surface in the report next to ``resumed_streams``."""
        return None

    def prefix_cache_snapshot(self):
        """Cumulative radix prefix-cache counters ``{"hits": tokens,
        "misses": tokens}`` from the target's telemetry
        (``tpu_prefix_cache_*_total``), or None when the transport
        cannot reach them.  Against a fleet router the counters are
        the churn-safe FLEET aggregate, so the generation profiler's
        window delta is the fleet-wide hit rate — the number that
        proves prefix-affinity routing keeps sibling prompts on warm
        replicas."""
        return None

    def spec_snapshot(self):
        """Cumulative speculative-decoding counters ``{"steps": n,
        "proposed": tokens, "accepted": tokens}`` from the target's
        telemetry (``tpu_spec_*``), or None when the transport cannot
        reach them (or the target predates speculation).  Against a
        fleet router the counters are the churn-safe FLEET aggregate,
        so the generation profiler's window delta is the fleet-wide
        acceptance rate."""
        return None

    # -- inference --------------------------------------------------------

    def prepare(self, model, input_sets):
        """Pre-serialize ``input_sets`` (list of name->np.ndarray dicts)
        into backend-native request handles.  Runs once per load level,
        OUTSIDE any measurement window — the timed path then only
        dispatches."""
        return [self._prepare_one(model, s) for s in input_sets]

    def _prepare_one(self, model, inputs):
        raise NotImplementedError

    def infer(self, prepared):
        """Synchronous inference of one prepared request; raises
        :class:`BackendError` on failure."""
        raise NotImplementedError

    def submit(self, prepared, on_done):
        """Non-blocking dispatch; ``on_done(error_or_None)`` fires on a
        completion thread.  Default implementation runs :meth:`infer`
        on a shared executor; backends with native async (gRPC)
        override."""
        executor = self._ensure_executor()

        def run():
            try:
                self.infer(prepared)
            except Exception as e:  # noqa: BLE001 — handed to on_done
                on_done(e)
                return
            on_done(None)

        executor.submit(run)

    def _ensure_executor(self):
        if self._executor is None:
            with self._executor_lock:
                if self._executor is None:
                    self._executor = ThreadPoolExecutor(
                        max_workers=self._executor_workers,
                        thread_name_prefix="perfanalyzer-" + self.kind,
                    )
        return self._executor

    # -- generation (decoupled streaming) ---------------------------------

    def generate_stream(self, model, inputs, parameters=None, stats=None):
        """Generator yielding the token count of each streamed response
        as it arrives (1 for the llama TOKEN-per-response contract).
        The generation profiler timestamps each yield: first yield =
        TTFT, gaps = inter-token latencies.

        ``stats`` (optional dict) receives per-stream bookkeeping the
        profiler folds into its report: backends that transparently
        reconnect+resume a dropped stream bump ``stats["resumes"]`` per
        reconnect, so under-chaos runs surface degradation instead of
        silently re-splicing broken streams."""
        raise NotImplementedError(
            "backend '{}' does not support generation mode".format(
                self.kind))

    def release_thread_resources(self):
        """Called by a generation worker as it exits; backends that
        pin per-thread resources (the gRPC stream client) free them
        here so swept levels don't accumulate channels."""

    # -- shared-memory data plane -----------------------------------------

    def shm_register(self, name, kind, key=None, raw_handle=None,
                     byte_size=0, device_ordinal=0):
        """Register a client-created region (``kind`` 'system' or
        'xla') with the serving target."""
        raise NotImplementedError(
            "backend '{}' does not support shared memory".format(self.kind))

    def shm_unregister(self, name, kind):
        raise NotImplementedError(
            "backend '{}' does not support shared memory".format(self.kind))

    def prepare_shm(self, model, input_refs, output_refs=None):
        """Prepared requests whose inputs are :func:`shm_input_ref`
        descriptors (one dict per input set) and whose outputs land in
        shared memory (``output_refs``: list of ``(name, region,
        byte_size, offset)``), for :meth:`infer`/``submit``."""
        raise NotImplementedError(
            "backend '{}' does not support shared memory".format(self.kind))

    def close(self):
        if self._executor is not None:
            self._executor.shutdown(wait=False)
            self._executor = None


def _np_wire_dtype(arr):
    from tritonclient.utils import np_to_triton_dtype

    if arr.dtype == np.object_:
        return "BYTES"
    return np_to_triton_dtype(arr.dtype)


def shm_input_ref(region, byte_size, offset, datatype, shape):
    """A shared-memory input reference: the value a prepared request
    carries instead of tensor bytes (the wire then moves ~40 bytes of
    descriptor where the data plane moves the tensor).  Understood by
    every backend's prepare/generate path and by the clients'
    ``generate_stream``."""
    return {
        "shared_memory_region": region,
        "shared_memory_byte_size": int(byte_size),
        "shared_memory_offset": int(offset),
        "datatype": datatype,
        "shape": list(shape),
    }


def _is_shm_ref(value):
    return isinstance(value, dict) and "shared_memory_region" in value


def _prepare_infer_inputs(mod, inputs, binary_data=None):
    """Shared input serialization for the socket backends: one
    ``InferInput`` per tensor, dtype mapped once (``binary_data`` is
    the HTTP wire toggle; gRPC's set_data_from_numpy takes no such
    argument).  A :func:`shm_input_ref` value becomes a shared-memory
    reference instead of inline bytes."""
    prepared = []
    for name, arr in inputs.items():
        if _is_shm_ref(arr):
            tin = mod.InferInput(name, list(arr["shape"]), arr["datatype"])
            tin.set_shared_memory(
                arr["shared_memory_region"],
                arr["shared_memory_byte_size"],
                arr.get("shared_memory_offset", 0))
            prepared.append(tin)
            continue
        tin = mod.InferInput(name, list(arr.shape), _np_wire_dtype(arr))
        if binary_data is None:
            tin.set_data_from_numpy(arr)
        else:
            tin.set_data_from_numpy(arr, binary_data=binary_data)
        prepared.append(tin)
    return prepared


def _response_token_count(outputs):
    """Tokens carried by one decoupled response, from its output list
    (dicts with name/shape).  Prefer a TOKEN/OUTPUT_IDS tensor's
    element count; fall back to 1 response = 1 step."""
    for entry in outputs or []:
        if entry.get("name") in ("TOKEN", "OUTPUT_IDS", "output_ids"):
            n = 1
            for d in entry.get("shape", []) or []:
                n *= max(1, _coerce_int(d, 1))
            data = entry.get("data")
            if isinstance(data, list) and data:
                n = len(data)
            return max(1, n)
    return 1


# -- in-process backend ----------------------------------------------------


class InProcessBackend(ClientBackend):
    """Drives ``tpuserver.core.InferenceServer`` with no transport at
    all — the floor every other backend's overhead is measured against
    (the reference's C-API backend role)."""

    kind = "inprocess"
    supports_generation = True

    def __init__(self, core, max_inflight=None):
        super().__init__(max_inflight)
        self.core = core

    def model_metadata(self, model):
        return self.core.model_metadata(model)

    def model_config(self, model):
        return self.core.model_config(model)

    def server_statistics(self, model):
        return self.core.model_statistics(model)

    def _prepare_one(self, model, inputs):
        from tpuserver.core import InferRequest

        return InferRequest(model, inputs=dict(inputs))

    def shm_register(self, name, kind, key=None, raw_handle=None,
                     byte_size=0, device_ordinal=0):
        from tpuserver.core import ServerError

        try:
            if kind == "system":
                self.core.register_system_shm(name, key, 0, byte_size)
            else:
                self.core.register_xla_shm(
                    name, raw_handle, device_ordinal, byte_size)
        except ServerError as e:
            raise BackendError(str(e)) from e

    def shm_unregister(self, name, kind):
        from tpuserver.core import ServerError

        try:
            if kind == "system":
                self.core.unregister_system_shm(name)
            else:
                self.core.unregister_xla_shm(name)
        except ServerError as e:
            raise BackendError(str(e)) from e

    def prepare_shm(self, model, input_refs, output_refs=None):
        return [("shm", model, dict(refs), list(output_refs or []))
                for refs in input_refs]

    def _resolve_refs(self, inputs):
        """Materialize shm references through the core's bounds-checked
        resolve path — for an in-process XLA region this returns the
        live device segment itself: the zero-copy plane."""
        out = {}
        for name, value in inputs.items():
            if _is_shm_ref(value):
                out[name] = self.core.read_shm_input(
                    value["shared_memory_region"],
                    value["shared_memory_byte_size"],
                    value.get("shared_memory_offset", 0),
                    value["datatype"],
                    value["shape"])
            else:
                out[name] = value
        return out

    def infer(self, prepared):
        from tpuserver.core import (
            InferRequest,
            RequestedOutput,
            ServerError,
        )

        try:
            if isinstance(prepared, tuple) and prepared[0] == "shm":
                _, model, refs, out_refs = prepared
                requested = None
                if out_refs:
                    requested = [
                        RequestedOutput(
                            n, binary_data=False, shm_region=region,
                            shm_byte_size=size, shm_offset=offset)
                        for n, region, size, offset in out_refs
                    ]
                req = InferRequest(
                    model, inputs=self._resolve_refs(refs),
                    requested_outputs=requested)
                self.core.infer(req)
                return
            # a fresh request object per call: InferRequest carries
            # per-call deadline state the core stamps on it
            req = InferRequest(prepared.model_name,
                               inputs=prepared.inputs)
            self.core.infer(req)
        except ServerError as e:
            raise BackendError(str(e)) from e

    def generate_stream(self, model, inputs, parameters=None, stats=None):
        from tpuserver.core import InferRequest, ServerError

        try:
            req = InferRequest(model, inputs=self._resolve_refs(inputs),
                               parameters=dict(parameters or {}))
            for resp in self.core.infer_stream(req):
                yield _response_token_count(
                    [spec for spec, _, _ in resp.outputs])
        except ServerError as e:
            raise BackendError(str(e)) from e

    def prefix_cache_snapshot(self):
        hits = misses = 0
        seen = False
        for stats in (self.core.health_snapshot().get("models")
                      or {}).values():
            if isinstance(stats, dict) and "prefix_hits" in stats:
                seen = True
                hits += _coerce_int(stats.get("prefix_hits"))
                misses += _coerce_int(stats.get("prefix_misses"))
        return {"hits": hits, "misses": misses} if seen else None

    def spec_snapshot(self):
        steps = proposed = accepted = 0
        seen = False
        for stats in (self.core.health_snapshot().get("models")
                      or {}).values():
            if isinstance(stats, dict) and "spec_steps" in stats:
                seen = True
                steps += _coerce_int(stats.get("spec_steps"))
                proposed += _coerce_int(stats.get("spec_proposed"))
                accepted += _coerce_int(stats.get("spec_accepted"))
        return ({"steps": steps, "proposed": proposed,
                 "accepted": accepted} if seen else None)


# -- socket-backend shared shm support --------------------------------------


class _TritonClientShmMixin:
    """Shared-memory support for the socket backends: the tritonclient
    http/grpc APIs are name-identical (register/unregister verbs,
    ``InferInput.set_shared_memory``, ``InferRequestedOutput``), so the
    register/unregister/prepare/infer plumbing lives once here —
    ``self.client`` is the transport client, ``self._mod`` its module."""

    def shm_register(self, name, kind, key=None, raw_handle=None,
                     byte_size=0, device_ordinal=0):
        from tritonclient.utils import InferenceServerException

        try:
            if kind == "system":
                self.client.register_system_shared_memory(
                    name, key, byte_size)
            else:
                self.client.register_xla_shared_memory(
                    name, raw_handle, device_ordinal, byte_size)
        except InferenceServerException as e:
            raise BackendError(str(e)) from e

    def shm_unregister(self, name, kind):
        from tritonclient.utils import InferenceServerException

        try:
            if kind == "system":
                self.client.unregister_system_shared_memory(name)
            else:
                self.client.unregister_xla_shared_memory(name)
        except InferenceServerException as e:
            raise BackendError(str(e)) from e

    def prepare_shm(self, model, input_refs, output_refs=None):
        prepared = []
        for refs in input_refs:
            tins = _prepare_infer_inputs(self._mod, refs)
            touts = None
            if output_refs:
                touts = []
                for name, region, size, offset in output_refs:
                    tout = self._mod.InferRequestedOutput(name)
                    tout.set_shared_memory(region, size, offset)
                    touts.append(tout)
            prepared.append((model, tins, touts))
        return prepared

    def infer(self, prepared):
        from tritonclient.utils import InferenceServerException

        model, infer_inputs = prepared[0], prepared[1]
        outputs = prepared[2] if len(prepared) > 2 else None
        try:
            if outputs is not None:
                self.client.infer(model, infer_inputs, outputs=outputs)
            else:
                self.client.infer(model, infer_inputs)
        except InferenceServerException as e:
            raise BackendError(str(e)) from e


# -- HTTP backend ----------------------------------------------------------

#: under-chaos reconnect budget for generation streams: the client
#: library's default 5-attempt budget backs off for ~1.5 s total,
#: which a supervised fleet's process-heal window outlasts when kill
#: faults COMPOSE (prefill + decode replica SIGKILLed in one campaign
#: cycle: two serial respawns + router re-admission).  Perf streams
#: must ride the heal out — the degradation is already reported as
#: resumed_streams/resume_events, never as a user-visible error
#: (found by tools/chaos_campaign.py --proof seed 10, pinned in
#: tests/test_chaos_campaign.py).
GENERATION_MAX_RECONNECTS = 10


class HttpBackend(_TritonClientShmMixin, ClientBackend):
    """``tritonclient.http`` against a live frontend; generation rides
    the ``/v2/models/{m}/generate_stream`` SSE endpoint."""

    kind = "http"
    supports_generation = True

    def __init__(self, url, max_inflight=None):
        super().__init__(max_inflight)
        import tritonclient.http as httpclient

        self._mod = httpclient
        self.url = url
        # the pooled-connection count must match the executor: fewer
        # connections than workers and requests queue INSIDE the
        # client, polluting the measured latency
        self.client = httpclient.InferenceServerClient(
            url, concurrency=self._executor_workers)
        # tri-state: None = not yet probed, False = target is a plain
        # replica (the 404 verdict is cached), True = fleet router
        self._is_router = None

    def _http_get(self, path):
        """One raw GET against the target's host:port, outside the
        triton client (these probe NON-KServe surfaces: /router/stats,
        /metrics).  Returns ``(status, body_bytes)``, or None on a
        port-less url or a transport/protocol error — the shared
        plumbing of every snapshot probe on this backend."""
        import http.client as _http_client

        host, sep, port = self.url.rpartition(":")
        if not sep or not port.isdigit():
            return None
        conn = _http_client.HTTPConnection(host, int(port), timeout=5)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            return resp.status, resp.read()
        except (OSError, ValueError, _http_client.HTTPException):
            return None
        finally:
            conn.close()

    def router_snapshot(self):
        """``/router/stats`` counters when the url fronts a
        FleetRouter; a plain replica answers 404 once and is never
        probed again."""
        if self._is_router is False:
            return None
        import json as _json

        got = self._http_get("/router/stats")
        if got is None:
            # port-less url can never reach a router: latch; a
            # transport error is transient: do not latch the verdict
            host, sep, port = self.url.rpartition(":")
            if not sep or not port.isdigit():
                self._is_router = False
            return None
        status, body = got
        if status != 200:
            self._is_router = False
            return None
        try:
            snap = _json.loads(body)
        except ValueError:
            return None
        self._is_router = True
        out = {
            "failovers": _coerce_int(snap.get("failovers")),
            "handoffs": _coerce_int(snap.get("handoffs")),
            "resumed_streams": _coerce_int(snap.get("resumed_streams")),
            "shed": _coerce_int(snap.get("shed")),
        }
        # tail-latency defense + router-HA counters: present only on
        # routers that carry them, so the delta attach can tell "zero
        # events" from "router predates the counters"
        for key in ("ejections", "hedges", "takeovers",
                    "recovered_generations"):
            if key in snap:
                out[key] = _coerce_int(snap.get(key))
        supervisor = snap.get("supervisor")
        if isinstance(supervisor, dict):
            # the router fronts a supervised fleet: its process-level
            # healing/scaling counters window-diff exactly like the
            # router's own (metrics.SUPERVISOR_COUNTERS)
            for key in ("replica_restarts", "scale_up_events",
                        "scale_down_events", "retired_replicas",
                        # crash-durability counters (ISSUE 18):
                        # presence-guarded like the rest so a
                        # supervisor predating the manifest never
                        # fabricates a delta
                        "adoptions", "clean_handovers",
                        "stale_children_reaped", "manifest_records"):
                if key in supervisor:
                    out["supervisor_" + key] = _coerce_int(
                        supervisor.get(key))
        return out

    def prefix_cache_snapshot(self):
        """The target's ``/metrics`` prefix-cache counters summed
        across label sets — against a router this is the fleet
        aggregate (replica restarts and churn already folded in)."""
        from tpuserver.metrics import parse_prometheus_text

        got = self._http_get("/metrics")
        if got is None or got[0] != 200:
            return None
        families = parse_prometheus_text(
            got[1].decode("utf-8", errors="replace"))
        out = {}
        for key, fam_name in (("hits", "tpu_prefix_cache_hits_total"),
                              ("misses", "tpu_prefix_cache_misses_total")):
            fam = families.get(fam_name)
            if fam is None:
                return None  # pre-paging server: no column
            out[key] = int(sum(v for _, _, v in fam["samples"]))
        return out

    def spec_snapshot(self):
        """The target's ``/metrics`` speculative-decoding counters
        summed across label sets — against a router this is the fleet
        aggregate (replica restarts and churn already folded in)."""
        from tpuserver.metrics import parse_prometheus_text

        got = self._http_get("/metrics")
        if got is None or got[0] != 200:
            return None
        families = parse_prometheus_text(
            got[1].decode("utf-8", errors="replace"))
        out = {}
        for key, fam_name in (
                ("steps", "tpu_spec_steps_total"),
                ("proposed", "tpu_spec_tokens_proposed_total"),
                ("accepted", "tpu_spec_tokens_accepted_total")):
            fam = families.get(fam_name)
            if fam is None:
                return None  # pre-speculation server: no column
            out[key] = int(sum(v for _, _, v in fam["samples"]))
        return out

    def model_metadata(self, model):
        return self.client.get_model_metadata(model)

    def model_config(self, model):
        return self.client.get_model_config(model)

    def server_statistics(self, model):
        return self.client.get_inference_statistics(model)

    def _prepare_one(self, model, inputs):
        return (model, _prepare_infer_inputs(
            self._mod, inputs, binary_data=True))

    def generate_stream(self, model, inputs, parameters=None, stats=None):
        """Stream over /generate_stream SSE via the client's resumable
        path: a connection dropped mid-generation transparently
        reconnects with ``Last-Event-ID`` and splices (same-endpoint
        resume); every reconnect is counted into ``stats["resumes"]``
        so chaos runs report ``resumed_streams`` instead of silently
        hiding the degradation."""
        from tritonclient.utils import InferenceServerException

        def on_reconnect(attempt, exc):
            if stats is not None:
                stats["resumes"] = stats.get("resumes", 0) + 1

        try:
            for event in self.client.generate_stream(
                    model, dict(inputs),
                    parameters=dict(parameters or {}),
                    max_reconnects=GENERATION_MAX_RECONNECTS,
                    on_reconnect=on_reconnect):
                yield _response_token_count(event.get("outputs"))
        except InferenceServerException as e:
            raise BackendError(str(e)) from e

    def close(self):
        super().close()
        self.client.close()


# -- gRPC backend ----------------------------------------------------------


class GrpcBackend(_TritonClientShmMixin, ClientBackend):
    """``tritonclient.grpc``; ``submit`` uses the client's native
    completion-callback async path (no extra thread per in-flight
    request), and generation rides a decoupled bidi stream."""

    kind = "grpc"
    supports_generation = True

    def __init__(self, url, max_inflight=None):
        super().__init__(max_inflight)
        # native async callbacks: the channel multiplexes without a
        # fixed in-flight ceiling
        self.capacity = None
        import tritonclient.grpc as grpcclient

        self._mod = grpcclient
        self.url = url
        self.client = grpcclient.InferenceServerClient(url)
        # generation streams are per-thread: one gRPC client owns at
        # most one bidi stream, and generation workers run concurrently
        self._stream_local = threading.local()
        self._stream_clients = []  # guarded-by: _stream_clients_lock
        self._stream_clients_lock = threading.Lock()

    def model_metadata(self, model):
        return self.client.get_model_metadata(model, as_json=True)

    def model_config(self, model):
        cfg = self.client.get_model_config(model, as_json=True)
        return cfg.get("config", cfg)

    def server_statistics(self, model):
        return self.client.get_inference_statistics(model, as_json=True)

    def _prepare_one(self, model, inputs):
        return (model, _prepare_infer_inputs(self._mod, inputs))

    def submit(self, prepared, on_done):
        model, infer_inputs = prepared[0], prepared[1]
        outputs = prepared[2] if len(prepared) > 2 else None

        def callback(result, error):
            on_done(error)

        if outputs is not None:
            self.client.async_infer(
                model, infer_inputs, callback, outputs=outputs)
        else:
            self.client.async_infer(model, infer_inputs, callback)

    def _thread_client(self):
        client = getattr(self._stream_local, "client", None)
        if client is None:
            client = self._mod.InferenceServerClient(self.url)
            self._stream_local.client = client
            with self._stream_clients_lock:
                self._stream_clients.append(client)
        return client

    def release_thread_resources(self):
        # a generation worker's thread-local channel dies with the
        # worker: a 1:64 sweep would otherwise hold every past level's
        # channels open until backend.close()
        client = getattr(self._stream_local, "client", None)
        if client is None:
            return
        self._stream_local.client = None
        with self._stream_clients_lock:
            try:
                self._stream_clients.remove(client)
            except ValueError:
                pass
        try:
            client.close()
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass

    def generate_stream(self, model, inputs, parameters=None, stats=None):
        """Decoupled bidi stream via the client's resumable path: a
        stream-level drop re-opens the stream with a resume token and
        splices (same-endpoint resume); reconnects are counted into
        ``stats["resumes"]`` for the profiler's ``resumed_streams``."""
        from tritonclient.utils import InferenceServerException

        client = self._thread_client()
        prepared = self._prepare_one(model, inputs)[1]

        def on_reconnect(attempt, exc):
            if stats is not None:
                stats["resumes"] = stats.get("resumes", 0) + 1

        try:
            for result in client.generate_stream(
                    model, prepared,
                    parameters=dict(parameters) if parameters else None,
                    max_reconnects=GENERATION_MAX_RECONNECTS,
                    on_reconnect=on_reconnect):
                resp = result.get_response()
                yield _response_token_count([
                    {"name": out.name, "shape": list(out.shape)}
                    for out in resp.outputs
                ])
        except InferenceServerException as e:
            raise BackendError(str(e)) from e

    def close(self):
        super().close()
        with self._stream_clients_lock:
            clients, self._stream_clients = self._stream_clients, []
        for client in clients:
            try:
                client.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        self.client.close()


# -- multi-replica pool backend --------------------------------------------


class PoolBackend(ClientBackend):
    """Drives ``tritonclient.EndpointPool`` over N replica URLs, so the
    failover/hedging layer itself can be put under measured load.

    Server statistics are summed across ALL replicas (each queried
    directly): the pool spreads requests over the fleet, so a single
    endpoint's counters would undercount the window.
    """

    kind = "pool"
    supports_generation = False

    def __init__(self, urls, max_inflight=None, **pool_kwargs):
        super().__init__(max_inflight)
        import tritonclient.http as httpclient

        self._mod = httpclient
        self.urls = list(urls)
        self.pool = httpclient.EndpointPool(self.urls, **pool_kwargs)
        # direct per-replica clients for statistics aggregation only
        self._stat_clients = [
            httpclient.InferenceServerClient(u) for u in self.urls
        ]

    def model_metadata(self, model):
        return self.pool.get_model_metadata(model)

    def model_config(self, model):
        return self.pool.get_model_config(model)

    def _per_replica_snapshots(self, model):
        from perfanalyzer.metrics import server_stats_snapshot

        snaps = {}
        for url, client in zip(self.urls, self._stat_clients):
            try:
                snaps[url] = server_stats_snapshot(
                    client.get_inference_statistics(model), model)
            except Exception:  # noqa: BLE001 — a drained/dead replica
                # must not abort the profile: load-testing failover IS
                # this backend's purpose; the delta pairing in
                # metrics.server_stats_delta drops replicas missing
                # from either end of a window.
                continue
        return snaps

    def stats_snapshot(self, model):
        """Summed flat snapshot PLUS the per-replica map: window deltas
        pair each replica with itself, so a replica dying or reviving
        mid-window never subtracts/adds its lifetime counters into one
        window's delta."""
        from perfanalyzer.metrics import zero_snapshot

        snaps = self._per_replica_snapshots(model)
        total = zero_snapshot()
        for snap in snaps.values():
            for key, val in snap.items():
                total[key] += val
        total["_replicas"] = snaps
        return total

    def server_statistics(self, model):
        # summed model_stats shape for API parity with the other
        # backends (the profiler itself uses stats_snapshot)
        total = self.stats_snapshot(model)
        merged = {
            "name": model,
            "inference_count": total["inference_count"],
            "execution_count": total["execution_count"],
            "inference_stats": {
                key: {
                    "count": total[key + "_count"],
                    "ns": total[key + "_ns"],
                }
                for key in ("success", "fail", "queue", "compute_input",
                            "compute_infer", "compute_output")
            },
        }
        return {"model_stats": [merged]}

    def _prepare_one(self, model, inputs):
        return (model, _prepare_infer_inputs(
            self._mod, inputs, binary_data=True))

    def infer(self, prepared):
        from tritonclient.utils import InferenceServerException

        model, infer_inputs = prepared
        try:
            self.pool.infer(model, infer_inputs)
        except InferenceServerException as e:
            raise BackendError(str(e)) from e

    def close(self):
        super().close()
        for client in self._stat_clients:
            try:
                client.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        self.pool.close()


# -- shared-memory infer-data manager ---------------------------------------


class ShmInferDataManager:
    """Client-side shared-memory staging for one perf_analyzer worker
    (role of the reference's ``InferDataManagerShm``): every input set
    of the rotation pool is written ONCE into a created-and-registered
    region outside any measurement window; the prepared requests then
    carry ``{region, offset}`` references, so the timed wire moves
    ~40-byte descriptors while the tensors ride the shm data plane.
    ``kind='xla'`` regions also park device segments — against an
    in-process server the resolve path returns the live ``jax.Array``
    itself (zero host copies).

    Region names are namespaced by a per-worker ``tag`` (default: the
    pid plus a random suffix), so N distributed workers driving one
    server never collide; :meth:`close` unregisters and unlinks every
    region this manager created — the per-worker region lifecycle.
    """

    def __init__(self, backend, kind, tag=None):
        if kind not in ("system", "xla"):
            raise ValueError(
                "shared-memory kind must be 'system' or 'xla' "
                "(got {!r})".format(kind))
        import os as _os
        import uuid as _uuid

        self.backend = backend
        self.kind = kind
        self.tag = "{}_{}".format(
            tag if tag is not None else _os.getpid(),
            _uuid.uuid4().hex[:6])
        self._regions = []  # (name, handle)

    # -- region lifecycle --------------------------------------------------

    def create_region(self, label, byte_size):
        """Create + register one region; returns ``(name, handle)``.
        The handle stays client-owned (this side reads rings / output
        regions through it)."""
        name = "pa_{}_{}".format(self.tag, label)
        if self.kind == "system":
            from tritonclient.utils import shared_memory as sysshm

            key = "/" + name
            handle = sysshm.create_shared_memory_region(
                name, key, byte_size)
            try:
                self.backend.shm_register(
                    name, "system", key=key, byte_size=byte_size)
            except Exception:
                sysshm.destroy_shared_memory_region(handle)
                raise
        else:
            from tritonclient.utils import xla_shared_memory as xshm

            handle = xshm.create_shared_memory_region(name, byte_size)
            try:
                self.backend.shm_register(
                    name, "xla", raw_handle=xshm.get_raw_handle(handle),
                    byte_size=byte_size)
            except Exception:
                xshm.destroy_shared_memory_region(handle)
                raise
        self._regions.append((name, handle))
        return name, handle

    def write(self, handle, arrays, offset=0):
        """Stage arrays at ``offset`` — for xla regions as device
        arrays when jax is importable (the zero-copy in-process form;
        the host window syncs automatically for a cross-process
        server), host bytes otherwise."""
        if self.kind == "system":
            from tritonclient.utils import shared_memory as sysshm

            sysshm.set_shared_memory_region(handle, arrays, offset=offset)
            return
        from tritonclient.utils import xla_shared_memory as xshm

        try:
            import jax.numpy as jnp

            arrays = [jnp.asarray(a) for a in arrays]
        except Exception:  # noqa: BLE001 — host staging still works
            pass
        xshm.set_shared_memory_region(handle, arrays, offset=offset)

    def close(self):
        """Unregister (server side) and unlink (client side) every
        region this worker created."""
        regions, self._regions = self._regions, []
        for name, handle in regions:
            try:
                self.backend.shm_unregister(name, self.kind)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
            try:
                if self.kind == "system":
                    from tritonclient.utils import shared_memory as sysshm

                    sysshm.destroy_shared_memory_region(handle)
                else:
                    from tritonclient.utils import (
                        xla_shared_memory as xshm,
                    )

                    xshm.destroy_shared_memory_region(handle)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass

    # -- staging -----------------------------------------------------------

    def stage_input_sets(self, input_sets):
        """Write the whole rotation pool into per-input regions (one
        region per input name, one slot per set) and return the
        reference dicts ``prepare_shm`` consumes — one per set."""
        sets = list(input_sets)
        if not sets:
            return []
        refs = [dict() for _ in sets]
        for name in sets[0]:
            arrays = [np.ascontiguousarray(s[name]) for s in sets]
            first = arrays[0]
            if first.dtype == np.object_:
                raise ValueError(
                    "shared-memory mode needs fixed-size dtypes; input "
                    "'{}' is BYTES".format(name))
            nbytes = first.nbytes
            if any(a.nbytes != nbytes for a in arrays):
                raise ValueError(
                    "input '{}': every pool set must share one shape "
                    "in shared-memory mode".format(name))
            label = "in_" + "".join(
                c for c in name.lower() if c.isalnum())[:24]
            region, handle = self.create_region(
                label, nbytes * len(arrays))
            datatype = _np_wire_dtype(first)
            for i, a in enumerate(arrays):
                self.write(handle, [a], offset=i * nbytes)
                refs[i][name] = shm_input_ref(
                    region, nbytes, i * nbytes, datatype, a.shape)
        return refs

    def stage_outputs(self, output_names, byte_size):
        """One output region with a ``byte_size`` slot per declared
        output; returns the ``(name, region, byte_size, offset)`` list
        ``prepare_shm`` consumes."""
        names = list(output_names)
        if not names:
            return []
        region, _ = self.create_region("out", byte_size * len(names))
        return [
            (n, region, byte_size, j * byte_size)
            for j, n in enumerate(names)
        ]


# -- factory ---------------------------------------------------------------


def create_backend(kind, url=None, urls=None, core=None,
                   max_inflight=None, **kwargs):
    """Build a backend by name (the CLI's ``--backend`` flag).

    ``http``/``grpc`` need ``url``; ``pool`` needs ``urls`` (list);
    ``inprocess`` needs ``core`` (an ``InferenceServer``).
    ``max_inflight`` sizes the backend's executor/connection pool so
    the requested load level actually reaches the server.
    """
    if kind == "inprocess":
        if core is None:
            raise ValueError("inprocess backend needs core=")
        return InProcessBackend(core, max_inflight=max_inflight)
    if kind == "http":
        if not url:
            raise ValueError("http backend needs url=")
        return HttpBackend(url, max_inflight=max_inflight, **kwargs)
    if kind == "grpc":
        if not url:
            raise ValueError("grpc backend needs url=")
        return GrpcBackend(url, max_inflight=max_inflight)
    if kind == "pool":
        if not urls:
            raise ValueError("pool backend needs urls=")
        return PoolBackend(urls, max_inflight=max_inflight, **kwargs)
    raise ValueError(
        "unknown backend '{}' (want http, grpc, inprocess, or "
        "pool)".format(kind))


# -- input synthesis -------------------------------------------------------


def build_input_pool(metadata, config, pool_size=16, batch_size=1,
                     shape_overrides=None, const_overrides=None, seed=0):
    """A rotating pool of DISTINCT random input sets for one model.

    Measurement hygiene (docs/benchmarking.md rule 1): every context
    rotates through distinct inputs so no (executable, values) pair
    repeats back-to-back.  Shapes come from the model metadata; dynamic
    dims (-1) must be pinned via ``shape_overrides`` (name -> dims).
    ``const_overrides`` (name -> scalar) fills an input with one fixed
    value instead of random data — for control inputs like a
    ``DELAY_US`` knob that must not be randomized.  Models with
    ``max_batch_size > 0`` get a leading ``batch_size`` axis, matching
    Triton config semantics.
    """
    from tritonclient.utils import triton_to_np_dtype

    shape_overrides = shape_overrides or {}
    const_overrides = const_overrides or {}
    batched = _coerce_int(config.get("max_batch_size", 0)) > 0
    pool = []
    for i in range(pool_size):
        rng = np.random.RandomState(seed + i)
        inputs = {}
        for spec in metadata.get("inputs", []):
            name = spec["name"]
            dims = list(shape_overrides.get(name, spec["shape"]))
            dims = [_coerce_int(d) for d in dims]
            if any(d < 1 for d in dims):
                raise ValueError(
                    "input '{}' has dynamic dims {}; pin them with "
                    "--shape {}:d1,d2,...".format(name, dims, name))
            if batched:
                dims = [batch_size] + dims
            datatype = spec["datatype"]
            if name in const_overrides:
                np_dtype = (np.object_ if datatype == "BYTES"
                            else triton_to_np_dtype(datatype))
                value = const_overrides[name]
                if datatype == "BYTES":
                    value = str(value).encode("utf-8")
                inputs[name] = np.full(dims, value, dtype=np_dtype)
            elif datatype == "BYTES":
                flat = np.array(
                    ["req{}-{}".format(i, j).encode("utf-8")
                     for j in range(int(np.prod(dims)))],
                    dtype=np.object_)
                inputs[name] = flat.reshape(dims)
            else:
                np_dtype = triton_to_np_dtype(datatype)
                if np_dtype is None:
                    raise ValueError(
                        "cannot synthesize datatype '{}' for input "
                        "'{}'".format(datatype, name))
                if np.issubdtype(np_dtype, np.integer):
                    inputs[name] = rng.randint(
                        0, 100, size=dims).astype(np_dtype)
                elif np_dtype == np.bool_:
                    inputs[name] = rng.randint(
                        0, 2, size=dims).astype(np.bool_)
                else:
                    inputs[name] = rng.rand(*dims).astype(np_dtype)
        pool.append(inputs)
    return pool
