"""Deprecated alias of :mod:`tritonclient.utils` (reference
tritonclientutils shim)."""

import warnings

warnings.warn(
    "The package `tritonclientutils` is deprecated; use "
    "`tritonclient.utils` instead.",
    DeprecationWarning,
    stacklevel=2,
)

from tritonclient.utils import *  # noqa: F401,F403,E402
