"""Seqlock-style write-completeness markers for the shm token ring.

The shm response ring (docs/resilience.md "Shared-memory data plane")
delivers each generation step as an 8-byte slot (int32 TOKEN + fp32
LOGPROB) that the client reads after the descriptor-only event names
its offset.  The slot itself carries no write-completeness marker: a
reader racing the writer (or reading a lane the ring already lapped)
can observe a torn or stale slot and deliver a silently wrong token.

This module is the one definition of the optional per-slot **seq
word** that closes that hole, shared by the writer (the llama model's
ring writer) and readers (perfanalyzer, chaos harnesses).  A request
opting in passes ``shm_ring_seq_base`` — the byte offset of a
parallel array of ``slots`` 4-byte words in the same region — and the
writer brackets every payload write seqlock-style:

1. stamp ``begin_word(seq)`` (odd — write in progress),
2. write the 8-byte payload slot,
3. stamp ``commit_word(seq)`` (even — payload for ``seq`` committed).

A reader accepts the payload only when the seq word equals
``commit_word(seq)`` for the seq it expects; anything else — the odd
in-progress word, a stale word from an earlier lap, zeros from a
never-written slot — is a torn/stale read.  The event still carries
the in-band TOKEN/LOGPROB tensors whenever the seq lane is active, so
a torn reader falls back to the in-band payload instead of surfacing
a wrong token; each fallback is counted in the process-wide
``tpu_shm_ring_torn_total`` counter (docs/observability.md).

Word encoding: ``2*seq + 1`` = write of ``seq`` in progress, ``2*seq
+ 2`` = ``seq`` committed.  Zero (a fresh region) never matches any
commit word, so an unwritten slot always reads as stale.
"""

import struct
import threading

__all__ = [
    "SEQ_WORD_BYTES", "begin_word", "commit_word", "seq_word_offset",
    "pack_word", "unpack_word", "slot_committed", "note_torn",
    "torn_total",
]

#: bytes per seq word: one little-endian uint32 per ring slot
SEQ_WORD_BYTES = 4

_WORD = struct.Struct("<I")
_WORD_MOD = 1 << 32


def begin_word(seq):
    """The odd in-progress marker stamped before slot ``seq``'s payload."""
    return (2 * int(seq) + 1) % _WORD_MOD


def commit_word(seq):
    """The even committed marker stamped after slot ``seq``'s payload."""
    return (2 * int(seq) + 2) % _WORD_MOD


def seq_word_offset(seq, slots, seq_base):
    """Byte offset of the seq word guarding ring slot ``seq % slots``,
    given the base of the seq-word array (``shm_ring_seq_base``)."""
    return int(seq_base) + (int(seq) % int(slots)) * SEQ_WORD_BYTES


def pack_word(word):
    """The 4-byte little-endian encoding of a seq word."""
    return _WORD.pack(int(word) % _WORD_MOD)


def unpack_word(data):
    """Decode a 4-byte seq word read from the region."""
    return _WORD.unpack(bytes(data)[:SEQ_WORD_BYTES])[0]


def slot_committed(word, seq):
    """Whether a seq word proves slot ``seq``'s payload is committed.

    False for the odd in-progress marker, for any earlier (or later —
    the ring lapped) sequence's word, and for zero (never written)."""
    return int(word) == commit_word(seq)


# -- torn-read accounting ----------------------------------------------------
#
# Readers live in client-side code with no server handle, so the count
# is a process-wide module counter; the server's metrics registry
# surfaces it via a scrape-time collector as tpu_shm_ring_torn_total
# (the registry stays a view, this stays the single account).

_lock = threading.Lock()
_torn = 0


def note_torn(count=1):
    """Record ``count`` torn/stale slot reads that fell back in-band."""
    global _torn
    with _lock:
        _torn += int(count)


def torn_total():
    """Process-wide torn/stale ring reads so far."""
    return _torn
