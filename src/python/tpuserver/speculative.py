"""Speculative decoding: n-gram drafts from the radix prefix cache.

Every decode iteration advances each slot by exactly one token — the
model's weights stream from HBM once per token per stream.  Speculative
decoding breaks that bound by *drafting* k candidate continuation
tokens cheaply, feeding all of them through ONE batched verify step
(``llama.paged_spec_step``), and keeping the longest prefix whose
greedy argmax agrees.  Under the greedy token-identity contract the
whole resilience stack is pinned on (resume / replay / handoff /
kv_park all re-feed ``prompt + history``), acceptance is exact: the
output token sequence is bitwise identical to single-token decoding,
only the number of HBM weight passes per emitted token changes.

The draft source costs no second model (prompt-lookup / n-gram
speculation): the scheduler's :class:`~tpuserver.paging.
RadixPrefixCache` already holds a content-addressed store of every
prompt and emitted history this replica served — a free n-gram model
over exactly the distribution being decoded.  :class:`NgramDrafter`
proposes, in priority order:

1. the tree's EXACT continuation of the stream's full context
   (:meth:`~tpuserver.paging.RadixPrefixCache.continuation`): for
   regenerate/extend/retry traffic the live context is a prefix of a
   sequence the replica already decoded, and the root-anchored walk
   is unambiguous where fixed-length n-grams collide (a run of one
   repeated token aliases every n-gram key to a single entry);
2. an n-gram index derived from the radix tree's cached token
   sequences (rebuilt only when the tree's ``version`` moves —
   lookups are dict probes, never tree walks), then
3. the stream's own ``prompt + history`` (classic prompt-lookup:
   repetitive and agentic traffic frequently repeats its own
   subsequences verbatim).

Lookups are STRICTLY read-only: the drafter never pins ref-counts,
never stamps LRU clocks, and never mutates the tree — a draft can
never change what eviction may reclaim, and a wrong draft can never
change output (greedy verify rejects it), only waste one sub-step of
compute.  Per-stream adaptive throttling in the scheduler stops paying
even that on streams whose acceptance rate is ~0.

Single-threaded by contract: the decode loop that owns the radix tree
is the only caller, so the drafter needs no locks (same discipline as
``tpuserver.paging``).
"""

__all__ = ["NgramDrafter"]

#: Longest suffix length the drafter matches on.  Longer suffixes are
#: tried first: a 4-gram match is far more predictive than a 1-gram.
DEFAULT_MAX_NGRAM = 4

#: Shortest suffix length worth matching.  2 keeps the 1-gram noise
#: floor out of the draft stream (a unigram match predicts little and
#: costs a verify sub-step per token drafted off it).
DEFAULT_MIN_NGRAM = 2

#: How far back the self-context scan looks for a prior occurrence of
#: the current suffix.  Bounds the per-step host cost on long
#: sequences; repetition beyond this window is rare enough to skip.
SELF_CONTEXT_WINDOW = 512


class NgramDrafter:
    """Read-only longest-suffix n-gram lookup over a radix prefix
    cache (plus the querying stream's own context).

    ``draft(tokens, k)`` proposes up to ``k`` continuation tokens for
    the sequence ending in ``tokens``: the tree's exact continuation
    of the full context when it is cached that deep, else the longest
    suffix of length ``max_ngram``..``min_ngram`` that has been seen
    before (in the tree, or earlier in ``tokens`` itself) contributes
    the tokens that followed it.  Returns ``[]`` when nothing matches
    — the scheduler then runs a plain single-token step for that
    slot.

    The tree-derived index is rebuilt lazily, keyed on the tree's
    ``version`` counter: a draft between tree mutations is a pure
    dict probe.
    """

    def __init__(self, radix=None, min_ngram=DEFAULT_MIN_NGRAM,
                 max_ngram=DEFAULT_MAX_NGRAM, max_draft=8):
        if min_ngram < 1 or max_ngram < min_ngram:
            raise ValueError(
                "need 1 <= min_ngram <= max_ngram (got {}..{})".format(
                    min_ngram, max_ngram))
        if max_draft < 1:
            raise ValueError(
                "max_draft must be >= 1 (got {})".format(max_draft))
        self._radix = radix
        self.min_ngram = int(min_ngram)
        self.max_ngram = int(max_ngram)
        self.max_draft = int(max_draft)
        self._index = {}
        self._version = None  # radix.version the index was built at
        # lifetime rebuild count (tests pin the lazy-rebuild contract)
        self.rebuilds = 0

    # -- tree index --------------------------------------------------------

    def _refresh(self):
        radix = self._radix
        if radix is None:
            return
        if self._version == radix.version:
            return
        index = {}
        lo, hi, cap = self.min_ngram, self.max_ngram, self.max_draft
        # deterministic iteration (dict order is insertion order and
        # the walk is structural), so two replicas with identical tree
        # histories build identical indices — the cross-replica twin
        # of the greedy-determinism contract
        for seq in radix.iter_sequences():
            length = len(seq)
            for end in range(lo, length):
                cont = seq[end:end + cap]
                if not cont:
                    continue
                for n in range(lo, hi + 1):
                    if n > end:
                        break
                    # last writer wins: later (more recently donated)
                    # sequences overwrite earlier continuations
                    index[tuple(seq[end - n:end])] = cont
        self._index = index
        self._version = radix.version
        self.rebuilds += 1

    @staticmethod
    def _self_lookup(tokens, n, cap):
        """PRIOR occurrence of the length-``n`` suffix inside
        ``tokens`` itself; returns the tokens that followed it (up to
        ``cap``), or None.  Prefers the most recent occurrence whose
        continuation is at least 2 tokens — occurrences near the end
        of the sequence truncate to a single token, and the caller
        drops the first proposal (its own next-token prediction), so
        a 1-token continuation drafts nothing."""
        suffix = tokens[-n:]
        hi = len(tokens) - n  # exclusive: skip the suffix's own match
        lo = max(0, len(tokens) - SELF_CONTEXT_WINDOW)
        short = None
        for i in range(hi - 1, lo - 1, -1):
            if tokens[i:i + n] == suffix:
                cont = tokens[i + n:i + n + cap]
                if len(cont) >= 2:
                    return cont
                if cont and short is None:
                    short = cont
        return short

    # -- the draft ---------------------------------------------------------

    def draft(self, tokens, k):
        """Up to ``k`` proposed continuation tokens for the sequence
        ending in ``tokens`` (any int iterable).  The tree's exact
        continuation of the full context outranks everything; below
        that the longest matching suffix wins, and the radix-tree
        index outranks self-context at equal length (fleet-served
        content covers more than one stream's history).  Pure lookup:
        no pinning, no mutation."""
        k = min(int(k), self.max_draft)
        if k <= 0:
            return []
        toks = [int(t) for t in tokens]
        if len(toks) < self.min_ngram:
            return []
        # exact-context continuation first: unambiguous where n-gram
        # keys collide (degenerate repetition), and exactly right for
        # regenerate/extend traffic whose context is a cached prefix
        if self._radix is not None:
            cont = self._radix.continuation(toks, k)
            if cont:
                return [int(t) for t in cont]
        self._refresh()
        best = None
        for n in range(min(self.max_ngram, len(toks)),
                       self.min_ngram - 1, -1):
            for cont in (self._index.get(tuple(toks[-n:])),
                         self._self_lookup(toks, n, self.max_draft)):
                if not cont:
                    continue
                if len(cont) >= 2:
                    return list(cont[:k])
                if best is None:
                    best = cont
        # nothing offered more than a single continuation token:
        # better than nothing (the verify step's bonus still rides)
        return list(best[:k]) if best else []
