"""Fleet-state manifest: crash durability for the supervisor itself.

PR 15 made the ROUTER tier crash-durable (journal.py); the supervisor
stayed an unsupervised singleton with amnesia — SIGKILL it and the
fleet silently stops healing, restart it and it respawns a perfectly
healthy fleet from scratch, burning restart budgets and cache warmth
for nothing.  This module records everything a SUCCESSOR supervisor
needs to *adopt* the running fleet instead:

- ``spawn``          — one replica spawn: index, role, port, scope,
  pid + process start-time identity token, argv template hash, and
  the spawn nonce the child advertises in ``/v2/health/stats``.
- ``restart`` / ``retire`` — the restart-budget window state (sliding
  ``restart_times``; CLOCK_MONOTONIC is system-wide on Linux, so the
  raw timestamps stay comparable across supervisor processes).
- ``scale``          — elastic up/down membership changes.
- ``router_spawn`` / ``router_restart`` / ``router_retire`` /
  ``promote`` — the supervised front tier's twin records (role swaps
  by stable port).
- ``config``         — fleet-level facts with no per-process home
  (the router journal directory a successor must RE-ATTACH).
- ``checkpoint``     — a full-state snapshot; the writer compacts on
  every checkpoint (fresh segment seeded with the snapshot, older
  segments pruned), so replay cost stays bounded.

**Wire format** — byte-identical to ``journal.py``: each record is
framed ``<u32 length><u32 crc32>`` + UTF-8 JSON, and recovery is
torn-tail-tolerant (a half-written final record truncates, never
fatal).  The framing/recovery helpers are imported from journal.py
rather than re-implemented, so the two logs can never drift.

**Adoption contract** (``FleetSupervisor`` start with a manifest):
a recorded child is claimed only when THREE independent identities
agree — the pid is alive AND its ``/proc`` start-time token matches
the record (pid reuse cannot forge this) AND its health snapshot
echoes the recorded spawn nonce (a foreign server squatting the port
cannot forge this).  :class:`AdoptedProcess` then wraps the non-child
pid with a ``subprocess.Popen``-shaped surface (``poll`` via the
start token — a zombie or recycled pid reads as exited; ``wait`` by
polling, since ``waitpid`` only works on children).

**Single-writer discipline**: an exclusive ``flock`` on
``<dir>/lock`` — two supervisors can NEVER both heal one fleet.  The
second comer gets a typed :class:`ManifestLocked` refusal (or waits,
with ``--takeover``); the kernel drops the lock the instant the
holder dies, so a SIGKILLed supervisor never wedges its successor.

The writer thread mirrors the journal writer's hot-path contract:
``append`` is one lock-free deque append; framing, I/O, fsync, and
compaction all happen on the (daemon AND joined — tpulint R5's
writer-thread companion check) ``fleet-manifest-writer`` thread.

See docs/resilience.md "Supervisor crash durability".
"""

import binascii
import fcntl
import json
import os
import signal
import subprocess
import threading
import time
import zlib
from collections import deque

from tpuserver.journal import (  # the SAME framing + recovery
    _FRAME, _list_segments, read_journal)

__all__ = [
    "AdoptedProcess",
    "ManifestLocked",
    "ManifestWriter",
    "acquire_manifest_lock",
    "argv_template_hash",
    "fold_manifest",
    "new_spawn_nonce",
    "process_start_token",
    "read_manifest",
    "release_manifest_lock",
]

#: counters a checkpoint snapshots and incremental records replay over
COUNTER_KEYS = (
    "replica_restarts", "scale_up_events", "scale_down_events",
    "retired_replicas", "router_restarts", "router_takeovers",
    "router_retired", "adoptions", "clean_handovers",
    "stale_children_reaped", "manifest_records",
)


def new_spawn_nonce():
    """A per-spawn identity nonce the child echoes back through
    ``/v2/health/stats`` — the port-squatter-proof leg of the adoption
    contract."""
    return binascii.hexlify(os.urandom(8)).decode("ascii")


def argv_template_hash(argv):
    """Stable hash of a command TEMPLATE (pre-substitution).  A
    successor started with a different template must not adopt
    children built from the old one — the running binary no longer
    matches what a respawn would produce."""
    blob = "\x00".join(str(a) for a in argv).encode("utf-8")
    return "{:08x}".format(zlib.crc32(blob) & 0xFFFFFFFF)


def process_start_token(pid):
    """The process's start-time identity token (``/proc/<pid>/stat``
    field 22, in clock ticks since boot), or None when the pid is
    gone, unreadable, or a ZOMBIE — a zombie is an exited process
    whose parent has not reaped it yet, never adoptable.  pid reuse
    cannot forge the token: a recycled pid starts at a later tick."""
    if not pid:
        return None
    try:
        with open("/proc/{}/stat".format(int(pid)), "rb") as fh:
            data = fh.read().decode("ascii", errors="replace")
    except (OSError, ValueError):
        return None
    # the comm field may contain spaces and parens; real fields resume
    # after the LAST ')'
    idx = data.rfind(")")
    if idx < 0:
        return None
    rest = data[idx + 2:].split()
    if not rest or rest[0] == "Z":
        return None
    try:
        return int(rest[19])  # field 22: starttime
    except (IndexError, ValueError):
        return None


class AdoptedProcess:
    """``subprocess.Popen``-shaped handle over a process THIS
    supervisor did not spawn (an adopted child).  Liveness goes
    through the start-time token so pid reuse reads as exited, not
    alive; the exit status of a non-child is unobservable, so a gone
    process reports returncode 0 (the supervisor only branches on
    exited-or-not)."""

    def __init__(self, pid, start_token):
        self.pid = int(pid)
        self.start_token = start_token
        self.returncode = None

    def poll(self):
        if self.returncode is not None:
            return self.returncode
        if (self.start_token is not None
                and process_start_token(self.pid) == self.start_token):
            return None
        self.returncode = 0
        return self.returncode

    def wait(self, timeout=None):
        deadline = (None if timeout is None
                    else time.monotonic() + max(0.0, timeout))
        while self.poll() is None:
            if deadline is not None and time.monotonic() >= deadline:
                raise subprocess.TimeoutExpired(
                    "adopted-pid-{}".format(self.pid), timeout)
            time.sleep(0.02)
        return self.returncode

    def send_signal(self, signum):
        if self.poll() is None:
            os.kill(self.pid, signum)

    def terminate(self):
        self.send_signal(signal.SIGTERM)

    def kill(self):
        self.send_signal(signal.SIGKILL)


# -- single-writer lock ------------------------------------------------------


class ManifestLocked(RuntimeError):
    """Another supervisor holds this fleet's manifest lock — two
    supervisors healing one fleet would double-spawn replicas and
    interleave manifest frames.  Retry with ``takeover=True`` to wait
    for the incumbent's handover (or death: the kernel releases the
    flock with the process)."""

    def __init__(self, directory, holder_pid=None):
        self.directory = directory
        self.holder_pid = holder_pid
        super().__init__(
            "fleet manifest {} is locked by another supervisor{} — "
            "refusing to double-supervise one fleet (use --takeover "
            "to wait for its handover)".format(
                directory,
                " (pid {})".format(holder_pid) if holder_pid else ""))


def _lock_path(directory):
    return os.path.join(directory, "lock")


def acquire_manifest_lock(directory, takeover=False, timeout_s=30.0):
    """Take the exclusive manifest flock; returns the held fd.  With
    ``takeover`` the call blocks (bounded by ``timeout_s``) until the
    incumbent releases — the supervised-handover path; without it a
    held lock is an immediate typed :class:`ManifestLocked`."""
    os.makedirs(directory, exist_ok=True)
    fd = os.open(_lock_path(directory), os.O_RDWR | os.O_CREAT, 0o644)
    deadline = time.monotonic() + max(0.0, timeout_s)
    while True:
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            if not takeover or time.monotonic() >= deadline:
                holder = None
                try:
                    with open(_lock_path(directory)) as fh:
                        holder = int(fh.read().strip() or 0) or None
                except (OSError, ValueError):
                    pass
                os.close(fd)
                raise ManifestLocked(directory, holder)
            time.sleep(0.05)
            continue
        # debuggability only — the FLOCK is the discipline, the pid in
        # the file is advisory (stale after a SIGKILL until retaken)
        try:
            os.ftruncate(fd, 0)
            os.pwrite(fd, str(os.getpid()).encode("ascii"), 0)
        except OSError:
            pass
        return fd


def release_manifest_lock(fd):
    if fd is None:
        return
    try:
        fcntl.flock(fd, fcntl.LOCK_UN)
    except OSError:
        pass
    try:
        os.close(fd)
    except OSError:
        pass


# -- reading + folding -------------------------------------------------------


def read_manifest(directory):
    """Replay every retained manifest record, oldest segment first;
    returns ``(records, truncated)`` with journal.py's torn-tail
    semantics (a half-written final record truncates, never fatal; a
    missing directory recovers to nothing)."""
    return read_journal(directory)


def _blank_state():
    return {
        "replicas": {},
        "routers": {},
        "counters": {key: 0 for key in COUNTER_KEYS},
        "next_index": 0,
        "router_journal": None,
        "journal_owned": False,
    }


def _rows_to_map(rows, key):
    out = {}
    for row in rows or []:
        try:
            out[int(row[key])] = dict(row)
        except (KeyError, TypeError, ValueError):
            continue
    return out


def fold_manifest(records):
    """Fold a record stream into the successor's fleet state:
    ``replicas`` (by index), ``routers`` (by stable port), restored
    counters, ``next_index``, and the router journal directory to
    re-attach.  A ``checkpoint`` resets the fold (that is the
    compaction contract); later records replay over it."""
    state = _blank_state()
    for rec in records:
        kind = rec.get("type")
        if kind == "checkpoint":
            snap = rec.get("state") or {}
            state = _blank_state()
            state["replicas"] = _rows_to_map(
                snap.get("replicas"), "index")
            state["routers"] = _rows_to_map(snap.get("routers"), "port")
            for key in COUNTER_KEYS:
                state["counters"][key] = int(
                    (snap.get("counters") or {}).get(key, 0))
            state["next_index"] = int(snap.get("next_index") or 0)
            state["router_journal"] = snap.get("router_journal")
            state["journal_owned"] = bool(snap.get("journal_owned"))
        elif kind == "spawn":
            index = int(rec["index"])
            row = state["replicas"].setdefault(index, {"index": index})
            row.update({
                "index": index,
                "role": rec.get("role"),
                "port": rec.get("port"),
                "scope": rec.get("scope"),
                "pid": rec.get("pid"),
                "start_token": rec.get("start_token"),
                "nonce": rec.get("nonce"),
                "argv_hash": rec.get("argv_hash"),
            })
            row.setdefault("restarts", 0)
            row.setdefault("restart_times", [])
            state["next_index"] = max(state["next_index"], index + 1)
        elif kind == "restart":
            row = state["replicas"].get(int(rec["index"]))
            if row is not None:
                row["restarts"] = int(rec.get("restarts") or 0)
                row["restart_times"] = list(
                    rec.get("restart_times") or [])
            state["counters"]["replica_restarts"] += 1
        elif kind == "retire":
            row = state["replicas"].get(int(rec["index"]))
            if row is not None:
                row["retired"] = True
                row["restart_times"] = list(
                    rec.get("restart_times") or row.get(
                        "restart_times") or [])
            state["counters"]["retired_replicas"] += 1
        elif kind == "scale":
            if rec.get("action") == "down":
                state["replicas"].pop(int(rec["index"]), None)
                state["counters"]["scale_down_events"] += 1
            else:
                # the paired spawn record carries the new replica
                state["counters"]["scale_up_events"] += 1
        elif kind == "router_spawn":
            port = int(rec["port"])
            row = state["routers"].setdefault(port, {"port": port})
            row.update({
                "port": port,
                "role": rec.get("role"),
                "partition": rec.get("partition"),
                "pid": rec.get("pid"),
                "start_token": rec.get("start_token"),
                "nonce": rec.get("nonce"),
            })
            row.setdefault("restarts", 0)
            row.setdefault("restart_times", [])
        elif kind == "router_restart":
            row = state["routers"].get(int(rec["port"]))
            if row is not None:
                row["restarts"] = int(rec.get("restarts") or 0)
                row["restart_times"] = list(
                    rec.get("restart_times") or [])
            state["counters"]["router_restarts"] += 1
        elif kind == "router_retire":
            row = state["routers"].get(int(rec["port"]))
            if row is not None:
                row["retired"] = True
            state["counters"]["router_retired"] += 1
        elif kind == "promote":
            for row in state["routers"].values():
                if row["port"] == rec.get("active_port"):
                    row["role"] = "active"
                    # partitioned tier: the promotion moved the dead
                    # active's partition onto the standby
                    if rec.get("partition") is not None:
                        row["partition"] = rec.get("partition")
                elif row["port"] == rec.get("standby_port"):
                    row["role"] = "standby"
                    if rec.get("partition") is not None:
                        row["partition"] = None
            state["counters"]["router_takeovers"] += 1
        elif kind == "config":
            if "router_journal" in rec:
                state["router_journal"] = rec.get("router_journal")
                state["journal_owned"] = bool(rec.get("journal_owned"))
    return state


# -- the writer --------------------------------------------------------------


class ManifestWriter:
    """The append side: a lock-free queue drained by one dedicated
    ``fleet-manifest-writer`` thread (daemon AND joined in
    :meth:`close` — the journal writer's lifecycle, pinned by tpulint
    R5's writer-thread companion check).

    Unlike the journal's time-based rotation, the manifest compacts on
    CHECKPOINT: a ``checkpoint`` record opens a fresh segment, lands
    as its first record, and prunes all but the newest two segments —
    everything before the snapshot is redundant by construction (the
    predecessor survives one extra generation so a torn checkpoint
    write still recovers from the previous fold)."""

    _RETAIN_SEGMENTS = 2

    def __init__(self, directory, flush_interval_s=0.02,
                 queue_capacity=8192):
        self._dir = directory
        os.makedirs(directory, exist_ok=True)
        self._flush_interval_s = float(flush_interval_s)
        # supervision-plane cadence, not a token hot path — but the
        # same lock-free enqueue contract keeps the monitor tick from
        # ever blocking on manifest I/O
        self._queue = deque(maxlen=int(queue_capacity))
        self._lock = threading.Lock()
        self._records = 0       # guarded-by: _lock
        self._checkpoints = 0   # guarded-by: _lock
        self._drain_passes = 0  # guarded-by: _lock
        self._closed = False    # guarded-by: _lock
        segments = _list_segments(directory)
        self._next_index = (segments[-1][0] + 1) if segments else 1
        self._fh = None  # writer-thread-owned
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="fleet-manifest-writer", daemon=True)
        self._thread.start()

    def append(self, record):
        """Enqueue one record dict; framing + I/O happen on the writer
        thread."""
        self._queue.append(record)
        self._wake.set()

    def checkpoint(self, state):
        """Enqueue a compacting full-state snapshot."""
        self.append({"type": "checkpoint", "state": state})

    # -- writer thread -----------------------------------------------------

    def _open_segment(self):
        if self._fh is not None:
            self._fh.close()
        path = os.path.join(
            self._dir, "seg-{:08d}.log".format(self._next_index))
        self._next_index += 1
        self._fh = open(path, "ab")
        segments = _list_segments(self._dir)
        for _idx, old in segments[:-self._RETAIN_SEGMENTS]:
            try:
                os.remove(old)
            except OSError:
                pass

    def _write_frames(self, frames):
        if not frames:
            return
        if self._fh is None:
            self._open_segment()
        self._fh.write(b"".join(frames))
        self._fh.flush()
        os.fsync(self._fh.fileno())

    @staticmethod
    def _frame(record):
        payload = json.dumps(
            record, separators=(",", ":")).encode("utf-8")
        return _FRAME.pack(
            len(payload), zlib.crc32(payload) & 0xFFFFFFFF) + payload

    def _drain(self):
        batch = []
        while True:
            try:
                batch.append(self._queue.popleft())
            except IndexError:
                break
        frames = []
        checkpoints = 0
        for record in batch:
            if record.get("type") == "checkpoint":
                # compaction boundary: flush what precedes it, rotate,
                # seed the fresh segment with the snapshot
                self._write_frames(frames)
                frames = []
                self._open_segment()
                checkpoints += 1
            frames.append(self._frame(record))
        self._write_frames(frames)
        with self._lock:
            self._records += len(batch)
            self._checkpoints += checkpoints
            self._drain_passes += 1

    def _run(self):
        while not self._stop.is_set():
            self._wake.wait(self._flush_interval_s)
            self._wake.clear()
            try:
                self._drain()
            except OSError:
                # a full/readonly disk degrades durability; it must
                # never take the supervision plane down
                pass
        try:
            self._drain()
        except OSError:
            pass
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    # -- lifecycle / observability -----------------------------------------

    def flush(self, timeout_s=5.0):
        """Block until everything enqueued so far is written + fsynced
        (a drain pass that STARTED after this call and left the queue
        empty covers every earlier enqueue)."""
        deadline = time.monotonic() + timeout_s
        with self._lock:
            target = self._drain_passes
        while time.monotonic() < deadline:
            self._wake.set()
            with self._lock:
                passes = self._drain_passes
            if not self._queue and passes > target:
                return True
            time.sleep(0.005)
        return False

    def close(self, timeout_s=5.0):
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=timeout_s)

    def stats(self):
        with self._lock:
            return {
                "records": self._records,
                "checkpoints": self._checkpoints,
                "queued": len(self._queue),
            }
