"""Chaos campaign engine: the shared invariant library + the seeded
fault scheduler (docs/resilience.md "Chaos campaigns").

PRs 13/15/16 each proved one defense with one single-fault
``tools/chaos_smoke.py`` mode, and each mode carried its own copy of
the same assertions — token identity against a fault-free reference,
gap/dup-free seq continuity, fleet-metric monotonicity, zero leaked
regions, fleet convergence.  Real incidents COMPOSE faults, and a
composed campaign needs those assertions as first-class, reusable
checks.  This module is that extraction, in two halves:

**Invariant library** — every check is named, takes an
:class:`InvariantRecorder`, and records a typed :class:`Violation`
(invariant name, context, message, structured details) instead of
ad-hoc prints.  ``chaos_smoke`` wires the recorder's sink to its
historical ``INVARIANT VIOLATED:`` stderr line, so every existing
mode keeps byte-identical CLI behavior; ``tools/chaos_campaign.py``
collects the same objects to print a minimized repro.  The catalog:

========================  ==================================================
``token_identity``         stream tokens equal the fault-free reference
``seq_continuity``         event seqs are gap-free and duplicate-free
``metric_monotonicity``    fleet-aggregated cumulative families (incl. the
                           ``tpu_disagg_*`` counters) never decrease or
                           vanish across cycles
                           (:class:`MetricsMonotonicityCheck`)
``counter_monotonicity``   a stats-dict counter set never moves backwards
``stream_drain``           the scheduler's live registry empties (zero
                           leaked streams) — :func:`wait_stream_drain`
``fleet_convergence``      the supervised fleet returns to its per-role
                           targets — :func:`wait_fleet_converged`
``journal_single_writer``  exactly one ACTIVE router process at a time
                           per partition (a horizontal tier holds one
                           active per journal subdirectory)
``partition_blast_radius``  an active's death touches ONLY its own
                           partition: sibling partitions' streams
                           complete with zero reconnects and gap-free
                           seqs — :func:`check_partition_blast_radius`
``shm_consistency``        ``xla_shm_status`` holds exactly the expected
                           regions (no stale ``kvexport/*`` leaks)
``thread_leak``            no non-daemon threads outlive the campaign
``supervisor_restarts_clean``  a restarted supervisor ADOPTS every
                           surviving child (same pid, same restart
                           budget, same role) and respawns ONLY the
                           actually-dead — :func:`check_supervisor_adoption`
========================  ==================================================

**Seeded fault scheduler** — :meth:`FaultSchedule.compose` turns the
existing fault arsenal (replica SIGKILL, router SIGKILL/SIGTERM, the
``slow``/``jitter``/``partition`` gray modes, mid-stream severs,
disagg prefill kills, shm faults) into a deterministic multi-fault
schedule: every draw comes from one ``random.Random(seed)``, so the
same ``--seed`` replays the exact schedule, and
:func:`minimized_repro` renders a failing campaign as ONE command
restricted to the cycles and fault kinds that had fired by the first
violation.  :data:`FAULT_KINDS` carries the composition matrix: kinds
in the same ``serial`` group never overlap inside a cycle (the
scheduler spaces them); everything else may overlap freely.

Clocks are monotonic throughout (tpulint R3) and this module spawns
no threads of its own (R5); checks never block under a lock (R2).
"""

import json
import random
import threading
import time

__all__ = [
    "Violation", "InvariantRecorder",
    "check_token_identity", "check_seq_continuity",
    "check_counters_monotonic", "MetricsMonotonicityCheck",
    "wait_stream_drain", "wait_fleet_converged",
    "check_journal_single_writer", "check_partition_blast_radius",
    "check_shm_consistency", "check_supervisor_adoption",
    "thread_baseline", "check_no_thread_leaks",
    "FAULT_KINDS", "ScheduledFault", "FaultSchedule",
    "minimized_repro", "CampaignRunner",
]


class Violation:
    """One typed invariant violation: which named invariant, where
    (free-form context like ``"fleet cycle 3"``), the human line the
    CLI prints, and structured details for programmatic consumers."""

    __slots__ = ("invariant", "context", "message", "details")

    def __init__(self, invariant, message, context="", details=None):
        self.invariant = invariant
        self.context = context
        self.message = message
        self.details = dict(details or {})

    def as_dict(self):
        return {
            "invariant": self.invariant,
            "context": self.context,
            "message": self.message,
            "details": self.details,
        }

    def __repr__(self):
        return "Violation({!r}, {!r})".format(self.invariant, self.message)

    def __str__(self):
        return self.message


class InvariantRecorder:
    """Collects :class:`Violation` objects; ``sink`` (if given) sees
    each one as it lands — chaos_smoke's sink prints the historical
    ``INVARIANT VIOLATED: {message}`` stderr line, the campaign tool's
    also remembers the first violation's cycle for the minimized
    repro.  Thread-safe: worker threads record concurrently."""

    def __init__(self, sink=None):
        self._lock = threading.Lock()
        self._violations = []  # guarded-by: _lock
        self._sink = sink

    def record(self, invariant, message, context="", **details):
        violation = Violation(invariant, message, context, details)
        with self._lock:
            self._violations.append(violation)
        # the sink runs OUTSIDE the lock: it may print, flush, or
        # re-enter the recorder without deadlocking a worker
        if self._sink is not None:
            self._sink(violation)
        return violation

    @property
    def violations(self):
        with self._lock:
            return list(self._violations)

    @property
    def count(self):
        with self._lock:
            return len(self._violations)

    @property
    def ok(self):
        return self.count == 0


# -- named invariant checks --------------------------------------------------


def check_token_identity(recorder, expected, actual, context="",
                         message=None, invariant="token_identity",
                         **details):
    """The token-identity oracle: a stream that claims success must be
    token-exact against the fault-free reference.  Returns True when
    the invariant held."""
    expected = list(expected)
    actual = list(actual)
    if actual == expected:
        return True
    recorder.record(
        invariant,
        message or "{}: tokens diverged: {} != {}".format(
            context, actual, expected),
        context=context, expected=expected, actual=actual, **details)
    return False


def check_seq_continuity(recorder, seqs, expected_len=None, context="",
                         message=None, invariant="seq_continuity",
                         **details):
    """Gap-free, duplicate-free seq numbering: the event seqs must be
    exactly ``0..n-1`` (and ``n == expected_len`` when given) — a gap
    is a lost token, a duplicate is a replayed one the splice failed
    to dedup."""
    seqs = list(seqs)
    ok = seqs == list(range(len(seqs)))
    if ok and expected_len is not None:
        ok = len(seqs) == expected_len
    if ok:
        return True
    recorder.record(
        invariant,
        message or "{}: seq gap/duplicate: {}".format(context, seqs),
        context=context, seqs=seqs, expected_len=expected_len, **details)
    return False


def check_counters_monotonic(recorder, before, after, keys, context="",
                             invariant="counter_monotonicity",
                             message_fmt=None, **details):
    """A stats-dict counter set (e.g. the router's ``disagg`` block)
    must never move backwards across a fault cycle.  ``message_fmt``
    receives ``(key, before_value, after_value)``."""
    ok = True
    for key in keys:
        prev, now = before[key], after[key]
        if now < prev:
            ok = False
            recorder.record(
                invariant,
                (message_fmt(key, prev, now) if message_fmt is not None
                 else "{}: counter {} moved backwards {} -> {}".format(
                     context, key, prev, now)),
                context=context, counter=key, before=prev, after=now,
                **details)
    return ok


class MetricsMonotonicityCheck:
    """Fleet-metric monotonicity (ISSUE 10's telemetry invariant,
    extracted from chaos_smoke's RouterMetricsCheck): ``GET /metrics``
    on the router must stay scrapeable under chaos, and its cumulative
    families (counters — including the ``tpu_disagg_*`` set —
    histogram buckets, and the ``*_total``/``*_count`` compatibility
    gauges) must NEVER decrease or vanish across cycles: the
    fleet-aggregated view survives replica restarts and membership
    churn without resetting.

    ``require_prefix`` additionally demands the paged-KV prefix-cache
    hit counter be present; ``prefix_hits`` holds the last scraped
    fleet-wide total so phases can assert a healed replica's cold
    radix cache RE-WARMS.

    :meth:`rebind` re-seeds the baseline against a NEW scrape target —
    the router-takeover edge: a freshly promoted standby is a
    different process whose owned counters legitimately start over, so
    carrying the dead active's baseline across a takeover would read
    as a (false) monotonicity violation."""

    def __init__(self, router_url, context, recorder,
                 require_prefix=False, invariant="metric_monotonicity"):
        host, _, port = router_url.rpartition(":")
        self.host, self.port = host, int(port)
        self.context = context
        self.recorder = recorder
        self.invariant = invariant
        self._prev = {}
        self.require_prefix = require_prefix
        self.prefix_hits = None

    def rebind(self, router_url):
        """Point at a new router process (standby takeover) and drop
        the old baseline — its owned counters restart legitimately."""
        host, _, port = router_url.rpartition(":")
        self.host, self.port = host, int(port)
        self._prev = {}

    def _scrape(self):
        import http.client

        conn = http.client.HTTPConnection(
            self.host, self.port, timeout=10)
        try:
            conn.request("GET", "/metrics")
            resp = conn.getresponse()
            if resp.status != 200:
                return None
            return resp.read().decode("utf-8", errors="replace")
        except (OSError, http.client.HTTPException):
            return None
        finally:
            conn.close()

    def scrapeable(self):
        """Probe-only: does the current target answer /metrics right
        now?  Records nothing — campaign runners use it to wait out a
        drain-exit/takeover settle before the real :meth:`check` (a
        SIGTERMed active can pass an 'up' convergence check and exit
        moments later; one-shot scraping that window reads as a false
        violation — found by campaign seeds 1/5/6)."""
        return self._scrape() is not None

    def check(self, cycle):
        from tpuserver.metrics import is_cumulative, parse_prometheus_text

        text = self._scrape()
        if text is None:
            self.recorder.record(
                self.invariant,
                "{} cycle {}: router /metrics not scrapeable".format(
                    self.context, cycle),
                context=self.context, cycle=cycle, kind="unscrapeable")
            return
        current = {}
        for name, fam in parse_prometheus_text(text).items():
            # the SAME cumulative-family rule the router's aggregator
            # folds by — the soak checks what the router aggregates
            if not is_cumulative(name, fam["type"]):
                continue
            for sample_name, labels, value in fam["samples"]:
                current[(sample_name,
                         tuple(sorted(labels.items())))] = value
        for key, prev in self._prev.items():
            now = current.get(key)
            if now is None:
                self.recorder.record(
                    self.invariant,
                    "{} cycle {}: fleet counter {} vanished from "
                    "/metrics (aggregation reset?)".format(
                        self.context, cycle, key),
                    context=self.context, cycle=cycle, kind="vanished",
                    counter=list(key[0:1]) and key[0])
            elif now < prev:
                self.recorder.record(
                    self.invariant,
                    "{} cycle {}: fleet counter {} DECREASED {} -> "
                    "{} across a replica restart".format(
                        self.context, cycle, key, prev, now),
                    context=self.context, cycle=cycle, kind="decreased",
                    counter=key[0], before=prev, after=now)
        self._prev = current
        hits = [v for (name, _labels), v in current.items()
                if name == "tpu_prefix_cache_hits_total"]
        if hits:
            self.prefix_hits = sum(hits)
        elif self.require_prefix:
            self.recorder.record(
                self.invariant,
                "{} cycle {}: tpu_prefix_cache_hits_total missing "
                "from the fleet /metrics view".format(
                    self.context, cycle),
                context=self.context, cycle=cycle,
                kind="prefix_missing")


def wait_stream_drain(stats_fn, timeout_s=10.0):
    """Zero leaked streams: poll a scheduler's ``stats()`` until its
    live registry empties (``live_streams == 0 and pending == 0``).
    Returns ``(drained, last_stats)``; the caller records the
    violation with its phase-specific wording when not drained."""
    deadline = time.monotonic() + timeout_s
    stats = stats_fn()
    while time.monotonic() < deadline:
        stats = stats_fn()
        if stats["live_streams"] == 0 and stats["pending"] == 0:
            return True, stats
    return False, stats


def wait_fleet_converged(stats_fn, membership_fn=None, restarts_above=None,
                         up=None, phase_up=None, members=None,
                         max_retired=0, timeout_s=60.0, poll_s=0.1):
    """Fleet convergence to per-role targets: poll the supervisor's
    ``stats()`` until every requested condition holds at once —

    - ``restarts_above``: ``replica_restarts`` moved PAST this
      baseline (the kill was actually noticed; guards against polling
      a stale 'up' before the monitor's next tick);
    - ``up``: total replicas up equals the target;
    - ``phase_up``: ``phase_replicas_up`` equals this per-role dict
      (role fleets heal WITH their role);
    - ``members``: router membership size equals this;
    - ``max_retired``: no replica burned its restart budget.

    Returns True once converged, False on timeout (the caller records
    the violation with the final stats)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        stats = stats_fn()
        ok = stats.get("retired_replicas", 0) <= max_retired
        if ok and restarts_above is not None:
            ok = stats.get("replica_restarts", 0) > restarts_above
        if ok and up is not None:
            ok = stats.get("up") == up
        if ok and phase_up is not None:
            ok = stats.get("phase_replicas_up") == phase_up
        if ok and members is not None and membership_fn is not None:
            ok = len({r["url"] for r in membership_fn()}) == members
        if ok:
            return True
        time.sleep(poll_s)
    return False


def check_journal_single_writer(recorder, routers, context="",
                                message=None,
                                invariant="journal_single_writer"):
    """Journal single-writer discipline, PER PARTITION: at most ONE
    router process may hold the active role for any one journal
    directory at a time — two actives appending to the same directory
    would interleave frames and corrupt recovery.  Unpartitioned rows
    (``partition`` absent/None — the single-active tier) all share one
    journal and form one group; a partitioned tier owns one journal
    subdirectory per partition, so one active PER PARTITION is the
    invariant.  ``routers`` is the supervisor's ``stats()["routers"]``
    list."""
    groups = {}
    for r in routers:
        if r.get("role") == "active" and r.get("state") == "up":
            groups.setdefault(r.get("partition"), []).append(r)
    bad = {part: rows for part, rows in groups.items()
           if len(rows) > 1}
    if not bad:
        return True
    recorder.record(
        invariant,
        message or "{}: multiple active routers sharing one journal "
        "(single-writer discipline broken) in partition(s) {}: "
        "{}".format(
            context, sorted(bad, key=str),
            [(r.get("pid"), r.get("role"), r.get("partition"))
             for r in routers]),
        context=context,
        active=sum(len(rows) for rows in bad.values()),
        routers=list(routers))
    return False


def check_partition_blast_radius(recorder, survivors, context="",
                                 message=None,
                                 invariant="partition_blast_radius"):
    """An active-router SIGKILL must blast ONLY its own partition:
    every stream homed on a SURVIVING partition rides through the
    sibling's death with ZERO reconnects and gap-free, duplicate-free
    seqs — the horizontal tier's whole point is that a front-door
    failure is a partition-sized event, never a fleet-sized one.
    ``survivors`` is a list of per-stream observation dicts:
    ``{"partition": k, "reconnects": n, "seqs": [...]}`` (``seqs``
    optional; when present it must be exactly ``0..n-1``)."""
    ok = True
    for i, row in enumerate(survivors):
        part = row.get("partition")
        reconnects = int(row.get("reconnects") or 0)
        if reconnects:
            ok = False
            recorder.record(
                invariant,
                message or "{}: stream {} on surviving partition {} "
                "reconnected {} time(s) during a sibling's kill — "
                "the blast radius leaked across partitions".format(
                    context, i, part, reconnects),
                context=context, stream=i, partition=part,
                reconnects=reconnects)
        seqs = row.get("seqs")
        if seqs is not None and list(seqs) != list(range(len(seqs))):
            ok = False
            recorder.record(
                invariant,
                message or "{}: stream {} on surviving partition {} "
                "has a seq gap/duplicate during a sibling's kill: "
                "{}".format(context, i, part, list(seqs)),
                context=context, stream=i, partition=part,
                seqs=list(seqs))
    return ok


def check_supervisor_adoption(recorder, before, survivors, stats,
                              context="",
                              invariant="supervisor_restarts_clean"):
    """A restarted supervisor must ADOPT, not respawn: every replica
    whose process survived the supervisor outage keeps its pid, its
    restart count, and its role (a changed pid is a double-spawn; a
    bumped restart count is a budget charged for a crash that never
    happened), every replica that actually died gets a NEW pid with
    exactly one restart charged, and the supervisor's ``adoptions``
    counter covers every survivor.  ``before`` maps replica index to
    its pre-outage ``stats()`` row, ``survivors`` is the set of
    indices whose process outlived the outage, ``stats`` is the
    successor's converged ``stats()``."""
    after = {r["index"]: r for r in stats.get("replicas", [])}
    ok = True
    for index, row in before.items():
        succ = after.get(index)
        if succ is None:
            ok = False
            recorder.record(
                invariant,
                "{}: replica {} vanished across the supervisor "
                "restart".format(context, index),
                context=context, index=index)
            continue
        if index in survivors:
            if succ.get("pid") != row.get("pid"):
                ok = False
                recorder.record(
                    invariant,
                    "{}: surviving replica {} was respawned (pid {} "
                    "-> {}) instead of adopted".format(
                        context, index, row.get("pid"),
                        succ.get("pid")),
                    context=context, index=index,
                    before_pid=row.get("pid"), after_pid=succ.get("pid"))
            if succ.get("restarts") != row.get("restarts"):
                ok = False
                recorder.record(
                    invariant,
                    "{}: surviving replica {} charged restart budget "
                    "({} -> {}) for a crash that never happened".format(
                        context, index, row.get("restarts"),
                        succ.get("restarts")),
                    context=context, index=index,
                    before=row.get("restarts"),
                    after=succ.get("restarts"))
        else:
            if succ.get("pid") == row.get("pid"):
                ok = False
                recorder.record(
                    invariant,
                    "{}: dead replica {} still shows its corpse pid "
                    "{}".format(context, index, row.get("pid")),
                    context=context, index=index, pid=row.get("pid"))
            if succ.get("restarts") != row.get("restarts", 0) + 1:
                ok = False
                recorder.record(
                    invariant,
                    "{}: dead replica {} should be charged exactly "
                    "one restart ({} -> {})".format(
                        context, index, row.get("restarts"),
                        succ.get("restarts")),
                    context=context, index=index,
                    before=row.get("restarts"),
                    after=succ.get("restarts"))
        if succ.get("role") != row.get("role"):
            ok = False
            recorder.record(
                invariant,
                "{}: replica {} changed role across the supervisor "
                "restart ({} -> {})".format(
                    context, index, row.get("role"), succ.get("role")),
                context=context, index=index,
                before_role=row.get("role"), after_role=succ.get("role"))
    if stats.get("adoptions", 0) < len(survivors):
        ok = False
        recorder.record(
            invariant,
            "{}: adoptions counter {} does not cover the {} "
            "surviving replica(s)".format(
                context, stats.get("adoptions", 0), len(survivors)),
            context=context, adoptions=stats.get("adoptions", 0),
            survivors=sorted(survivors))
    return ok


def check_shm_consistency(recorder, status, expected, context="",
                          message=None, invariant="shm_consistency"):
    """Zero leaked kv-export regions/pages: ``xla_shm_status`` must
    hold exactly the expected region names — a lingering
    ``kvexport/*`` entry is a leaked server-owned export, a missing
    client region is a dropped registration."""
    status = set(status)
    expected = set(expected)
    if status == expected:
        return True
    recorder.record(
        invariant,
        message or "{}: xla_shm_status inconsistent: {} != {}".format(
            context, sorted(status), sorted(expected)),
        context=context, status=sorted(status),
        expected=sorted(expected),
        leaked=sorted(status - expected),
        missing=sorted(expected - status))
    return False


def thread_baseline():
    """Idents of live non-daemon threads — capture BEFORE a campaign;
    :func:`check_no_thread_leaks` diffs against it after."""
    return {t.ident for t in threading.enumerate()
            if not t.daemon and t.ident is not None}


def check_no_thread_leaks(recorder, baseline, grace_s=5.0, context="",
                          invariant="thread_leak"):
    """Zero leaked non-daemon threads: anything alive past the grace
    window that was not in the baseline would outlive the process's
    intended shutdown (the conftest thread-leak guard's twin, usable
    outside pytest)."""
    deadline = time.monotonic() + grace_s
    while time.monotonic() < deadline:
        leaked = [t for t in threading.enumerate()
                  if not t.daemon and t.ident not in baseline]
        if not leaked:
            return True
        for t in leaked:
            t.join(timeout=0.1)
    leaked = [t for t in threading.enumerate()
              if not t.daemon and t.ident not in baseline]
    if not leaked:
        return True
    recorder.record(
        invariant,
        "{}: leaked non-daemon thread(s) after {:.1f}s grace: "
        "{}".format(context, grace_s, [t.name for t in leaked]),
        context=context, threads=[t.name for t in leaked])
    return False


# -- seeded fault scheduler --------------------------------------------------

#: The schedulable fault arsenal and its COMPOSITION MATRIX.  Each kind
#: maps to ``(description, serial_group)``: kinds sharing a non-None
#: serial group never overlap within a cycle — the scheduler spaces
#: them ``serial_gap_s`` apart (two process kills racing each other
#: would leave no fleet to assert invariants against; two router-tier
#: faults racing would fight over one takeover).  Kinds with group
#: ``None`` may overlap anything: gray latency, severed streams, and
#: half-open partitions composing OVER a kill is exactly the
#: interaction surface the campaigns exist to probe.
FAULT_KINDS = {
    "replica_sigkill": (
        "SIGKILL one up replica process (no drain, no warning); the "
        "supervisor must heal it back to target", "kill"),
    "prefill_sigkill": (
        "SIGKILL the PREFILL-role replica mid-handoff; orphaned "
        "splits must degrade to the fused path invisibly", "kill"),
    "supervisor_sigkill": (
        "SIGKILL the SUPERVISOR itself mid-traffic; the fleet keeps "
        "serving unsupervised, and the restarted supervisor must "
        "ADOPT the survivors from its manifest (no double-spawn, no "
        "budget burn)", "kill"),
    "router_sigkill": (
        "SIGKILL the ACTIVE router; the standby must take over and "
        "recover resume state from the journal", "router"),
    "router_sigterm": (
        "SIGTERM the ACTIVE router (drain-first path): in-flight "
        "streams finish or hand off before exit", "router"),
    "active_router_sigkill": (
        "SIGKILL one ACTIVE of a partitioned multi-router tier; the "
        "standby must promote INTO the dead router's partition while "
        "sibling partitions' streams ride through untouched "
        "(partition_blast_radius)", "router"),
    "gray_slow": (
        "turn one replica gray: alive to probes, orders of magnitude "
        "slower to serve (faults 'slow' / stub infer_delay_ms)", None),
    "gray_jitter": (
        "deterministic pseudo-random per-event latency on one "
        "replica (faults 'jitter')", None),
    "stream_sever": (
        "sever live streams mid-generation with no terminal event; "
        "clients must auto-resume via Last-Event-ID", None),
    "partition": (
        "half-open partition: the connection stays accepted but "
        "reads stall (faults 'partition' / stub partition_ms)", None),
    "shm_fault": (
        "fail a shared-memory read (faults 'core.shm_read'); the "
        "request gets a typed error, siblings keep decoding", None),
}

#: minimum in-cycle spacing between two faults of the same serial group
SERIAL_GAP_S = 0.5


class ScheduledFault:
    """One scheduled injection: fire ``kind`` at ``offset_s`` into
    cycle ``cycle``.  ``pick`` is a deterministic victim-selector draw
    (injectors use ``ups[pick % len(ups)]`` so the same seed kills the
    same replica) and ``params`` carries per-kind knobs drawn from the
    same seeded stream (gray delay, sever count, ...)."""

    __slots__ = ("cycle", "kind", "offset_s", "pick", "params")

    def __init__(self, cycle, kind, offset_s, pick, params=None):
        self.cycle = cycle
        self.kind = kind
        self.offset_s = float(offset_s)
        self.pick = int(pick)
        self.params = dict(params or {})

    def as_dict(self):
        return {
            "cycle": self.cycle,
            "kind": self.kind,
            "offset_s": round(self.offset_s, 4),
            "pick": self.pick,
            "params": self.params,
        }

    def __repr__(self):
        return ("ScheduledFault(cycle={}, kind={!r}, offset_s={:.3f}, "
                "pick={})".format(self.cycle, self.kind, self.offset_s,
                                  self.pick))


class FaultSchedule:
    """A deterministic multi-fault schedule: every draw comes from ONE
    ``random.Random(seed)`` consumed in a fixed order, so the same
    ``(seed, kinds, cycles, window_s)`` replays the exact same
    schedule — the property the deterministic-replay test pins and
    the minimized repro relies on."""

    def __init__(self, seed, kinds, cycles, window_s, entries):
        self.seed = int(seed)
        self.kinds = tuple(kinds)
        self.cycles = int(cycles)
        self.window_s = float(window_s)
        self.entries = list(entries)

    @classmethod
    def compose(cls, seed, kinds, cycles, window_s=2.0,
                serial_gap_s=SERIAL_GAP_S):
        """Compose ``kinds`` into ``cycles`` fault windows.  Each kind
        fires once per cycle at a seeded offset inside
        ``[0.1, 0.7 * window_s]``; kinds sharing a serial group are
        re-spaced at least ``serial_gap_s`` apart (in sorted-kind
        order, so the spacing itself is deterministic too)."""
        kinds = list(kinds)
        unknown = [k for k in kinds if k not in FAULT_KINDS]
        if unknown:
            raise ValueError(
                "unknown fault kind(s) {}; known: {}".format(
                    unknown, sorted(FAULT_KINDS)))
        rng = random.Random(int(seed))
        entries = []
        for cycle in range(int(cycles)):
            cycle_entries = []
            # fixed draw order (the requested kind order), so the
            # stream of rng consumptions — and thus every later draw —
            # is a pure function of (seed, kinds, cycles)
            for kind in kinds:
                offset = rng.uniform(0.1, max(0.15, 0.7 * window_s))
                pick = rng.randrange(1 << 30)
                params = {}
                if kind in ("gray_slow", "gray_jitter"):
                    params["delay_ms"] = rng.choice((120, 200, 320))
                elif kind == "stream_sever":
                    params["streams"] = rng.choice((1, 2, 3))
                elif kind == "partition":
                    params["stall_ms"] = rng.choice((150, 300, 500))
                cycle_entries.append(
                    ScheduledFault(cycle, kind, offset, pick, params))
            # serialization pass: same-group entries get ordered,
            # spaced offsets (sorted draws assigned in kind order)
            groups = {}
            for entry in cycle_entries:
                group = FAULT_KINDS[entry.kind][1]
                if group is not None:
                    groups.setdefault(group, []).append(entry)
            for group_entries in groups.values():
                if len(group_entries) < 2:
                    continue
                offsets = sorted(e.offset_s for e in group_entries)
                last = None
                for entry, offset in zip(group_entries, offsets):
                    if last is not None and offset < last + serial_gap_s:
                        offset = last + serial_gap_s
                    entry.offset_s = offset
                    last = offset
            cycle_entries.sort(key=lambda e: (e.offset_s, e.kind))
            entries.extend(cycle_entries)
        return cls(seed, kinds, cycles, window_s, entries)

    def for_cycle(self, cycle):
        return [e for e in self.entries if e.cycle == cycle]

    def kinds_through(self, cycle):
        """The distinct kinds that fire in cycles ``0..cycle`` — the
        restricted fault set a minimized repro replays."""
        seen = []
        for entry in self.entries:
            if entry.cycle <= cycle and entry.kind not in seen:
                seen.append(entry.kind)
        return seen

    def to_json(self):
        return json.dumps({
            "seed": self.seed,
            "kinds": list(self.kinds),
            "cycles": self.cycles,
            "window_s": self.window_s,
            "entries": [e.as_dict() for e in self.entries],
        }, indent=1, sort_keys=True)

    def describe(self):
        lines = ["schedule seed={} cycles={} window={:.1f}s".format(
            self.seed, self.cycles, self.window_s)]
        for entry in self.entries:
            lines.append(
                "  cycle {} +{:6.3f}s  {:<16} pick={} {}".format(
                    entry.cycle, entry.offset_s, entry.kind, entry.pick,
                    entry.params or ""))
        return "\n".join(lines)


def minimized_repro(seed, failing_cycle, kinds, tool="tools/chaos_campaign.py",
                    extra_args=()):
    """The single command that replays a failing campaign minimized to
    its first violation: same seed (the schedule prefix is identical —
    compose() draws per cycle in order), cycles truncated to the
    failing one, faults restricted to the kinds that had fired."""
    parts = ["python", tool, "--seed", str(int(seed)),
             "--cycles", str(int(failing_cycle) + 1),
             "--faults", ",".join(kinds)]
    parts.extend(str(a) for a in extra_args)
    return " ".join(parts)


class CampaignRunner:
    """Executes one cycle of a :class:`FaultSchedule` against a
    registry of injectors (``kind -> callable(entry)``).  The runner
    sleeps to each entry's offset and fires it in the calling thread —
    the caller owns worker traffic and per-cycle invariant checks;
    this owns only deterministic fault timing.  Injector exceptions
    are recorded as ``injector_error`` violations rather than killing
    the campaign mid-schedule (a broken injector must not read as a
    passed cycle)."""

    def __init__(self, schedule, injectors, recorder):
        self.schedule = schedule
        self.injectors = dict(injectors)
        self.recorder = recorder
        missing = [e.kind for e in schedule.entries
                   if e.kind not in self.injectors]
        if missing:
            raise ValueError(
                "no injector for scheduled kind(s): {}".format(
                    sorted(set(missing))))
        self.fired = []  # entries actually fired, in order

    def run_cycle(self, cycle):
        """Fire every entry of ``cycle`` at its offset; returns the
        entries fired."""
        start = time.monotonic()
        fired = []
        for entry in self.schedule.for_cycle(cycle):
            delay = entry.offset_s - (time.monotonic() - start)
            if delay > 0:
                time.sleep(delay)
            try:
                self.injectors[entry.kind](entry)
            except Exception as e:  # noqa: BLE001 — a broken injector
                # must surface as a violation, not a silent pass
                self.recorder.record(
                    "injector_error",
                    "cycle {}: injector {} failed: {}: {}".format(
                        cycle, entry.kind, type(e).__name__, e),
                    context="cycle {}".format(cycle), kind=entry.kind)
            fired.append(entry)
            self.fired.append(entry)
        return fired
