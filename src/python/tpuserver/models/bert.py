"""BERT-base ensemble family (BASELINE config #4: tokenizer -> encoder).

Mirrors the reference's ensemble pattern (model_config ensemble_scheduling,
reference model_parser.h ENSEMBLE scheduler type): a host-side tokenizer
model (BYTES -> INT32 ids, KIND_CPU) feeding a TPU encoder (bidirectional
transformer, bf16, learned positions, GELU MLP) that emits a pooled
embedding.  The tokenizer is a hash-based wordpiece-lite — the bench
exercises protocol + ensemble scheduling + device round trip, not MLM
accuracy.
"""

import threading

import numpy as np

from tpuserver.core import JaxModel, Model, TensorSpec

SEQ_LEN = 128
VOCAB = 30522  # bert-base vocab size
D_MODEL = 768
N_LAYERS = 12
N_HEADS = 12
D_FF = 3072


class BertTokenizerModel(Model):
    """TEXT (BYTES [1]) -> INPUT_IDS/ATTENTION_MASK (INT32 [SEQ_LEN]).

    Whitespace split + stable hash into the vocab (ids 1000+ so specials
    stay clear); [CLS]=101 / [SEP]=102 framing like wordpiece."""

    name = "bert_tokenizer"
    platform = "python"
    backend = "python"
    max_batch_size = 8
    inputs = (TensorSpec("TEXT", "BYTES", [1]),)
    outputs = (
        TensorSpec("INPUT_IDS", "INT32", [SEQ_LEN]),
        TensorSpec("ATTENTION_MASK", "INT32", [SEQ_LEN]),
    )

    def execute(self, inputs, request):
        import zlib

        texts = np.asarray(inputs["TEXT"]).reshape(-1)
        ids = np.zeros((len(texts), SEQ_LEN), dtype=np.int32)
        mask = np.zeros((len(texts), SEQ_LEN), dtype=np.int32)
        for row, raw in enumerate(texts):
            text = raw.decode("utf-8") if isinstance(raw, bytes) else str(raw)
            tokens = [101]  # [CLS]
            for word in text.lower().split():
                tokens.append(
                    1000 + (zlib.crc32(word.encode("utf-8")) % (VOCAB - 1100))
                )
                if len(tokens) >= SEQ_LEN - 1:
                    break
            tokens.append(102)  # [SEP]
            ids[row, : len(tokens)] = tokens
            mask[row, : len(tokens)] = 1
        batched = np.asarray(inputs["TEXT"]).ndim > 1
        if not batched:
            return {"INPUT_IDS": ids[0], "ATTENTION_MASK": mask[0]}
        return {"INPUT_IDS": ids, "ATTENTION_MASK": mask}


class BertEncoderModel(JaxModel):
    """INPUT_IDS/ATTENTION_MASK -> POOLED [D_MODEL] (CLS-token tanh head),
    bf16 on TPU."""

    name = "bert_encoder"
    platform = "jax"
    backend = "jax"
    max_batch_size = 8
    inputs = (
        TensorSpec("INPUT_IDS", "INT32", [SEQ_LEN]),
        TensorSpec("ATTENTION_MASK", "INT32", [SEQ_LEN]),
    )
    outputs = (TensorSpec("POOLED", "FP32", [D_MODEL]),)

    def __init__(self, seed=0):
        super().__init__()
        self._params = None
        self._seed = seed
        self._params_lock = threading.Lock()

    def prepare(self):
        # eager param init (outside any jit trace; see JaxModel.prepare)
        self._get_params()

    def _get_params(self):
        if self._params is not None:
            return self._params
        with self._params_lock:
            if self._params is None:
                import jax
                import jax.numpy as jnp

                key = jax.random.PRNGKey(self._seed)

                def dense(key, shape, fan_in):
                    return (
                        jax.random.normal(key, shape, jnp.float32)
                        / np.sqrt(fan_in)
                    ).astype(jnp.bfloat16)

                keys = iter(jax.random.split(key, 16 + 8 * N_LAYERS))
                layers = []
                for _ in range(N_LAYERS):
                    layers.append(
                        {
                            "wq": dense(next(keys), (D_MODEL, D_MODEL),
                                        D_MODEL),
                            "wk": dense(next(keys), (D_MODEL, D_MODEL),
                                        D_MODEL),
                            "wv": dense(next(keys), (D_MODEL, D_MODEL),
                                        D_MODEL),
                            "wo": dense(next(keys), (D_MODEL, D_MODEL),
                                        D_MODEL),
                            "ln1": jnp.ones((D_MODEL,), jnp.bfloat16),
                            "w_in": dense(next(keys), (D_MODEL, D_FF),
                                          D_MODEL),
                            "w_out": dense(next(keys), (D_FF, D_MODEL),
                                           D_FF),
                            "ln2": jnp.ones((D_MODEL,), jnp.bfloat16),
                        }
                    )
                self._params = {
                    "tok_embed": dense(next(keys), (VOCAB, D_MODEL),
                                       D_MODEL),
                    "pos_embed": dense(next(keys), (SEQ_LEN, D_MODEL),
                                       D_MODEL),
                    "layers": layers,
                    "pool_w": dense(next(keys), (D_MODEL, D_MODEL), D_MODEL),
                }
        return self._params

    def jax_fn(self, INPUT_IDS, ATTENTION_MASK):
        import jax
        import jax.numpy as jnp

        params = self._get_params()
        ids = INPUT_IDS
        mask = ATTENTION_MASK
        squeeze = ids.ndim == 1
        if squeeze:
            ids = ids[None, :]
            mask = mask[None, :]
        B, T = ids.shape
        hd = D_MODEL // N_HEADS
        x = params["tok_embed"][ids] + params["pos_embed"][None, :T]
        bias = jnp.where(
            mask[:, None, None, :] > 0, 0.0, -1e9
        ).astype(jnp.float32)

        def ln(x, g):
            xf = x.astype(jnp.float32)
            mu = xf.mean(-1, keepdims=True)
            var = ((xf - mu) ** 2).mean(-1, keepdims=True)
            return ((xf - mu) * jax.lax.rsqrt(var + 1e-12)).astype(
                x.dtype
            ) * g

        for layer in params["layers"]:
            h = ln(x, layer["ln1"])
            q = (h @ layer["wq"]).reshape(B, T, N_HEADS, hd)
            k = (h @ layer["wk"]).reshape(B, T, N_HEADS, hd)
            v = (h @ layer["wv"]).reshape(B, T, N_HEADS, hd)
            s = jnp.einsum(
                "bqhd,bkhd->bhqk", q, k,
                preferred_element_type=jnp.float32,
            ) / np.sqrt(hd)
            p = jax.nn.softmax(s + bias, axis=-1)
            attn = jnp.einsum(
                "bhqk,bkhd->bqhd", p.astype(x.dtype), v
            ).reshape(B, T, D_MODEL)
            x = x + attn @ layer["wo"]
            h = ln(x, layer["ln2"])
            x = x + jax.nn.gelu(h @ layer["w_in"]) @ layer["w_out"]
        pooled = jnp.tanh(
            (x[:, 0, :] @ params["pool_w"]).astype(jnp.float32)
        )
        if squeeze:
            pooled = pooled[0]
        return {"POOLED": pooled}

    def warmup(self):
        self.execute(
            {
                "INPUT_IDS": np.zeros((1, SEQ_LEN), np.int32),
                "ATTENTION_MASK": np.ones((1, SEQ_LEN), np.int32),
            },
            None,
        )


class BertEnsembleModel(Model):
    """TEXT -> POOLED via tokenizer + encoder (ensemble_scheduling steps,
    the shape the reference's perf_analyzer calls ENSEMBLE)."""

    name = "bert_ensemble"
    platform = "ensemble"
    backend = ""
    max_batch_size = 8
    inputs = (TensorSpec("TEXT", "BYTES", [1]),)
    outputs = (TensorSpec("POOLED", "FP32", [D_MODEL]),)
    ensemble_steps = [
        {
            "model_name": "bert_tokenizer",
            "model_version": -1,
            "input_map": {"TEXT": "TEXT"},
            "output_map": {
                "INPUT_IDS": "ids",
                "ATTENTION_MASK": "mask",
            },
        },
        {
            "model_name": "bert_encoder",
            "model_version": -1,
            "input_map": {"INPUT_IDS": "ids", "ATTENTION_MASK": "mask"},
            "output_map": {"POOLED": "POOLED"},
        },
    ]
