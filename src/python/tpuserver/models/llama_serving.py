"""Decoupled llama generation serving model (BASELINE config #5: token-by-
token generate streaming with TPU-shm KV handles).

One request carries the prompt ids; the model prefills the KV cache in one
batched pass, then streams one sampled token per response over the
decoupled channel (ModelStreamInfer).  Generation runs as a jitted
decode_step per token — static shapes, cache donated, so steady-state cost
is one device dispatch per token.

Execution modes:

- **single-device** (default): plain jits on the default device.
- **tensor-parallel** (``mesh=`` with a ``tp`` axis): the same compute
  via ``llama.make_tp_serving`` — Megatron column/row-split weights,
  kv-head-sharded cache (``llama.cache_spec``), XLA-inserted collectives.
  The served model IS the sharded jit; no separate "distributed backend".
- **int8 weights** (``quantize=True``): weights quantize on load
  (``llama.quantize_params``) so the 8B preset (16 GB bf16) serves within
  a single 16 GB-HBM v5e chip.

KV-cache persistence: a request parameter ``kv_cache_region`` naming a
registered XLA shared-memory region makes the model park the finished KV
cache (a device-resident ``jax.Array`` — sharded across the mesh in tp
mode) in that region and, on a follow-up request with the same parameter
and ``kv_cache_resume=True``, continue generation from it without
re-prefilling — the TPU-shm analogue of the reference's CUDA-shm tensor
passing, applied to generation state.

Continuous batching (``max_slots > 1``): generation routes through the
``tpuserver.scheduler.DecodeScheduler`` — a block-paged KV pool
(``page_size``-token pages, ``kv_pages`` bound, radix prefix cache
deduplicating shared prompt prefixes, chunked prefill past
``prefill_chunk_tokens``) and a background loop running one batched
decode step for ALL in-flight streams per iteration, admitting waiting
requests into freed slots mid-flight as long as pages remain.  Greedy
tokens are identical to the single-stream path (test-enforced);
``max_slots=1`` (the default) keeps the original single-stream
pipelined path byte-for-byte, so existing tests and BENCH numbers stay
reproducible.  An optional ``eos_id`` request parameter ends a
generation early on that token (emitted, then the slot retires and is
reused), on both paths.  See docs/resilience.md "Paged KV cache &
radix prefix cache".
"""

import threading

import numpy as np

from tpuserver.core import Model, TensorSpec
from tpuserver.models import llama


class LlamaGenerateModel(Model):
    """PROMPT_IDS int32[-1], MAX_TOKENS int32[1] -> stream of
    (TOKEN int32[1], LOGPROB fp32[1]) responses."""

    name = "llama_generate"
    platform = "jax"
    backend = "jax"
    max_batch_size = 0
    decoupled = True
    inputs = (
        TensorSpec("PROMPT_IDS", "INT32", [-1]),
        TensorSpec("MAX_TOKENS", "INT32", [1]),
    )
    outputs = (
        TensorSpec("TOKEN", "INT32", [1]),
        TensorSpec("LOGPROB", "FP32", [1]),
    )

    # tokens greedy-decoded per device dispatch: the steady state is
    # dispatch-latency-bound on remote chips, so a scanned chunk
    # amortizes the host<->device hop over several tokens (each token
    # still streams as its own decoupled response)
    decode_chunk = 8

    def __init__(self, cfg=None, max_seq=512, server=None,
                 decode_chunk=None, mesh=None, quantize=False,
                 max_slots=1, max_pending=None, fault_scope=None,
                 step_timeout_s=None, max_restarts=5,
                 restart_window_s=60.0, restart_backoff_s=0.05,
                 replay_ttl_s=60.0, replay_capacity=256,
                 page_size=16, kv_pages=None, prefill_chunk_tokens=256,
                 prefix_cache=True, kv_export=False,
                 target_queue_ms=None, shed_interval_ms=100.0,
                 spec_tokens=None):
        self._cfg = cfg or llama.tiny(vocab=2048)
        # replica identity threaded to the scheduler's fault-injection
        # points (multi-replica chaos harnesses)
        self._fault_scope = fault_scope
        self._max_seq = max_seq
        self._server = server  # for kv_cache_region xla-shm lookups
        self._mesh = mesh  # tensor-parallel serving when set (tp axis)
        self._quantize = bool(quantize)
        self._params = None
        self._prefill = None
        self._decode = None
        self._decode_chunk = None
        if max_slots < 1:
            raise ValueError(
                "max_slots must be >= 1 (got {})".format(max_slots))
        self._max_slots = int(max_slots)
        self._max_pending = max_pending  # admission-queue bound override
        # adaptive (CoDel-style) queue shedding, threaded to
        # DecodeScheduler — None keeps the fixed max_pending cliff only
        self._target_queue_ms = target_queue_ms
        self._shed_interval_ms = shed_interval_ms
        # supervisor / replay-buffer knobs, threaded to DecodeScheduler
        # (docs/resilience.md "Self-healing & stream resume")
        self._step_timeout_s = step_timeout_s
        self._max_restarts = max_restarts
        self._restart_window_s = restart_window_s
        self._restart_backoff_s = restart_backoff_s
        self._replay_ttl_s = replay_ttl_s
        self._replay_capacity = replay_capacity
        # paged-KV geometry (continuous batching only): fixed-size KV
        # pages, pool bound (None = max_slots full-length sequences —
        # byte-identical capacity to the old slotted cache), chunked-
        # prefill bound, and the radix prefix-cache toggle
        self._page_size = page_size
        self._kv_pages = kv_pages
        self._prefill_chunk_tokens = prefill_chunk_tokens
        self._prefix_cache = prefix_cache
        # default for the per-request ``kv_park`` parameter: park a
        # disconnected resumable generation's gathered KV pages as a
        # server-owned XLA-shm region, so a same-host resume attaches
        # and re-scatters instead of re-prefilling prompt + history
        self._kv_export = bool(kv_export)
        # speculative decoding: candidate tokens drafted (from the
        # radix prefix cache) and verified per batched step; 0 is
        # today's single-token path byte-for-byte, None defers to the
        # TPUSERVER_SPEC_TOKENS environment variable (default 0) so a
        # whole fleet — or an unmodified test run — can flip it on
        self._spec_tokens = spec_tokens
        self._scheduler = None  # DecodeScheduler when max_slots > 1
        # continuous-batching models interleave many streams' responses;
        # the frontends must not serialize their stream requests
        self.concurrent_decoupled = self._max_slots > 1
        if decode_chunk is not None:
            if decode_chunk < 1:
                raise ValueError(
                    "decode_chunk must be >= 1 (got {})".format(
                        decode_chunk))
            self.decode_chunk = decode_chunk
        if mesh is not None and "tp" not in mesh.shape:
            raise ValueError(
                "llama serving mesh needs a 'tp' axis (got {})".format(
                    dict(mesh.shape)))
        self._lock = threading.Lock()

    def attach_server(self, server):
        self._server = server

    def _ensure_compiled(self):
        if self._params is not None:
            return
        with self._lock:
            if self._params is None:
                import functools

                import jax

                if self._quantize:
                    # quantize-on-load: init + quantize on HOST so the
                    # bf16 weights never exist in HBM — the point for
                    # the 8B preset, whose 16 GB of bf16 exceeds a v5e
                    # chip but whose ~8 GB int8 form fits
                    cpu = jax.devices("cpu")[0]
                    with jax.default_device(cpu):
                        params = llama.quantize_params(
                            llama.init_params(
                                jax.random.PRNGKey(0), self._cfg
                            )
                        )
                    if self._mesh is None:
                        params = jax.device_put(
                            params, jax.devices()[0])
                else:
                    params = llama.init_params(
                        jax.random.PRNGKey(0), self._cfg
                    )
                if self._mesh is not None:
                    param_sh, _, _ = llama.serving_shardings(
                        self._mesh, self._cfg, quantized=self._quantize
                    )
                    params = jax.device_put(params, param_sh)
                if self._max_slots > 1:
                    # continuous batching: a background loop owns a
                    # slotted cache and all device state; the fns below
                    # stay None (the single-stream path is not built)
                    from tpuserver.scheduler import DecodeScheduler

                    fns = llama.make_scheduler_fns(
                        self._cfg, self._max_seq, self._max_slots,
                        mesh=self._mesh, quantized=self._quantize,
                        page_size=self._page_size,
                        kv_pages=self._kv_pages,
                    )
                    server = self._server
                    kv_hooks = {}
                    if server is not None:
                        # the park-attach data plane rides the server's
                        # XLA-shm registry; per-request ``kv_park``
                        # (or the model-level default) turns it on
                        kv_hooks = dict(
                            kv_export=server.export_kv_region,
                            kv_import=server.import_kv_region,
                            kv_discard=server.drop_kv_region)
                    self._scheduler = DecodeScheduler(
                        fns, params, self._max_slots, self._max_seq,
                        max_pending=self._max_pending,
                        **kv_hooks,
                        fault_scope=self._fault_scope,
                        step_timeout_s=self._step_timeout_s,
                        max_restarts=self._max_restarts,
                        restart_window_s=self._restart_window_s,
                        restart_backoff_s=self._restart_backoff_s,
                        replay_ttl_s=self._replay_ttl_s,
                        replay_capacity=self._replay_capacity,
                        prefill_chunk_tokens=self._prefill_chunk_tokens,
                        prefix_cache=self._prefix_cache,
                        target_queue_ms=self._target_queue_ms,
                        shed_interval_ms=self._shed_interval_ms,
                        spec_tokens=self._spec_tokens,
                        # queue-wait/step latency histograms land in
                        # the attached server's /metrics registry
                        # (lock-free observes — the decode loop never
                        # pays a lock to be observable)
                        metrics=getattr(self._server, "metrics", None),
                        metric_labels={"model": self.name},
                    )
                elif self._mesh is not None:
                    init_cache, prefill_fn, chunk_fn = (
                        llama.make_tp_serving(
                            self._mesh, self._cfg,
                            chunk=self.decode_chunk,
                            quantized=self._quantize,
                        )
                    )
                    step_fn = llama.make_tp_step(
                        self._mesh, self._cfg,
                        quantized=self._quantize,
                    )
                    self._init_cache = (
                        lambda: init_cache(1, self._max_seq)
                    )
                    self._prefill = prefill_fn
                    self._decode = step_fn
                    self._decode_chunk = chunk_fn
                else:
                    self._init_cache = lambda: llama.init_kv_cache(
                        self._cfg, 1, self._max_seq
                    )
                    self._prefill = jax.jit(
                        functools.partial(llama.prefill, cfg=self._cfg)
                    )
                    self._decode = jax.jit(
                        functools.partial(
                            llama.decode_step, cfg=self._cfg),
                        donate_argnums=(1,),
                    )
                    self._decode_chunk = jax.jit(
                        functools.partial(
                            llama.decode_chunk, cfg=self._cfg,
                            chunk=self.decode_chunk),
                        donate_argnums=(1,),
                    )
                self._params = params

    def warmup(self):
        self._ensure_compiled()

    def _kv_region(self, request):
        from tpuserver.core import ServerError

        name = request.parameters.get("kv_cache_region")
        if not name:
            return None
        if self._server is None:
            raise ServerError(
                "model '{}' has no server attached; kv_cache_region "
                "requires a registered XLA shm region".format(self.name)
            )
        return self._server.xla_shm_region(name)

    @staticmethod
    def _resume_state(request, region):
        """(parked cache segment or None, resume position) for a
        ``kv_cache_resume`` request — the one copy of the resume
        parameter contract, shared by both serving paths."""
        if region is None or not request.parameters.get("kv_cache_resume"):
            return None, 0
        parked = region.handle.get_jax_segment(0)
        if parked is None:
            return None, 0
        if "kv_cache_position" not in request.parameters:
            raise ValueError(
                "kv_cache_resume requires kv_cache_position (the "
                "sequence position the parked cache was left at)"
            )
        return parked, int(request.parameters["kv_cache_position"])

    def _ring_writer(self, request):
        """``(region_name, write, seq_guarded)`` for a request carrying
        a token-ring descriptor (``shm_ring_region`` +
        ``shm_ring_slots`` [+ ``shm_ring_offset`` base]), or None.
        ``write(seq, token, logprob)`` lands the step in its ring slot
        (``seq %% slots``) through the server's bounds-checked shm
        plumbing and returns the slot's byte offset — the descriptor
        the decoupled event carries instead of the tensors.

        ``shm_ring_seq_base`` opts the request into seqlock write-
        completeness markers (tpuserver.shm_ring): every payload write
        is bracketed by a begin/commit word in the parallel seq-word
        array at that base offset, so a reader can detect a torn or
        stale slot and fall back to the in-band payload — which the
        events then also carry (``seq_guarded=True``)."""
        name = request.parameters.get("shm_ring_region")
        if not name:
            return None
        server = self._server
        if server is None:
            from tpuserver.core import ServerError

            raise ServerError(
                "model '{}' has no server attached; shm_ring_region "
                "requires a registered shared-memory region".format(
                    self.name)
            )
        slots = int(request.parameters.get("shm_ring_slots") or 0)
        if slots < 1:
            raise ValueError(
                "shm_ring_region requires shm_ring_slots >= 1 (the "
                "ring geometry travels with the request)")
        base = int(request.parameters.get("shm_ring_offset") or 0)
        slot_bytes = server.SHM_RING_SLOT_BYTES
        seq_base = request.parameters.get("shm_ring_seq_base")

        if seq_base is None:
            def write(seq, token, logprob):
                off = base + (seq % slots) * slot_bytes
                server.write_shm_ring_slot(name, off, token, logprob)
                return off

            return name, write, False

        from tpuserver import shm_ring

        seq_base = int(seq_base)

        def write(seq, token, logprob):
            off = base + (seq % slots) * slot_bytes
            word_off = shm_ring.seq_word_offset(seq, slots, seq_base)
            server.write_shm_ring_seq_word(
                name, word_off, shm_ring.begin_word(seq))
            server.write_shm_ring_slot(name, off, token, logprob)
            server.write_shm_ring_seq_word(
                name, word_off, shm_ring.commit_word(seq))
            return off

        return name, write, True

    @staticmethod
    def _emit_token(token, logprob, seq, ring_write, seq_guarded=False):
        """One decoupled response: the TOKEN/LOGPROB tensors in-band,
        or — on the shm token ring — just the slot descriptor (the
        event shrinks to ``seq -> offset``; the tensors live in the
        client-registered region).  A seq-guarded ring keeps the
        tensors in-band too: the payload a reader that detects a torn
        slot falls back to."""
        if ring_write is None:
            return {
                "TOKEN": np.array([token], dtype=np.int32),
                "LOGPROB": np.array([logprob], dtype=np.float32),
            }
        from tpuserver.core import RESPONSE_PARAMS_KEY

        off = ring_write(seq, int(token), float(logprob))
        params = {"seq": seq}
        params["shm_ring_offset"] = off
        event = {RESPONSE_PARAMS_KEY: params}
        if seq_guarded:
            event["TOKEN"] = np.array([token], dtype=np.int32)
            event["LOGPROB"] = np.array([logprob], dtype=np.float32)
        return event

    def execute_stream(self, inputs, request):
        import jax

        self._ensure_compiled()
        raw_prompt = inputs["PROMPT_IDS"]
        prompt_dev = None
        if isinstance(raw_prompt, jax.Array):
            # the zero-copy request plane: a device-resident XLA-shm
            # segment view feeds prefill directly — the ids are never
            # staged through the host on the single-stream path, and
            # the scheduler's cold prefill consumes the view on device
            prompt_dev = (raw_prompt if raw_prompt.ndim == 1
                          else raw_prompt.reshape(-1))
            prompt = None
            prompt_len = int(prompt_dev.shape[0])
        else:
            prompt = np.asarray(raw_prompt).reshape(-1).astype(np.int32)
            prompt_len = len(prompt)
        max_tokens = int(np.asarray(inputs["MAX_TOKENS"]).reshape(-1)[0])
        if prompt_len == 0:
            raise ValueError("PROMPT_IDS must be non-empty")
        eos_id = request.parameters.get("eos_id")
        eos_id = int(eos_id) if eos_id is not None else None

        ring = self._ring_writer(request)
        ring_write = ring[1] if ring is not None else None
        seq_guarded = ring[2] if ring is not None else False
        # pin every referenced region for the stream's lifetime: a
        # concurrent unregister becomes a typed 409 conflict instead of
        # a crash (or a silent write into freed memory) mid-generation
        pinned = []
        server = self._server
        try:
            if server is not None:
                names = {n for n in (
                    ring[0] if ring is not None else None,
                    request.parameters.get("kv_cache_region"),
                ) if n}
                # regions the frontend resolved inputs from (the
                # prompt's live device view) pin too
                names.update(getattr(request, "shm_input_regions", ()))
                for name in names:
                    server.pin_shm_region(name)
                    pinned.append(name)
            if self._scheduler is not None:
                # continuous batching: hand the request to the shared
                # decode loop; tokens stream back as the batched steps
                # produce them
                if prompt is None:
                    # the scheduler's bookkeeping (radix keys, replay
                    # history) needs host ids; ONE device->host read —
                    # the prefill itself still consumes the device view
                    prompt = np.asarray(prompt_dev).reshape(-1).astype(
                        np.int32)
                yield from self._execute_scheduled(
                    prompt, max_tokens, eos_id, request, ring_write,
                    prompt_dev=prompt_dev, seq_guarded=seq_guarded,
                )
            else:
                yield from self._execute_single(
                    prompt, prompt_dev, prompt_len, max_tokens, eos_id,
                    request, ring_write, seq_guarded,
                )
        finally:
            for name in pinned:
                server.unpin_shm_region(name)

    def _execute_single(self, prompt, prompt_dev, prompt_len, max_tokens,
                        eos_id, request, ring_write,
                        seq_guarded=False):
        import jax
        import jax.numpy as jnp

        region = self._kv_region(request)
        parked, pos = self._resume_state(request, region)
        cache = None
        if parked is not None:
            # decode_step donates its cache argument; copy so the parked
            # array in the region registry stays valid even if this
            # stream dies mid-generation.
            cache = jnp.copy(parked)
        if cache is None:
            cache = self._init_cache()
            pos = 0
        if pos + prompt_len + max_tokens > self._max_seq:
            raise ValueError(
                "position ({}) + prompt ({}) + max_tokens ({}) exceeds max "
                "sequence {}".format(
                    pos, prompt_len, max_tokens, self._max_seq
                )
            )

        if prompt_dev is not None:
            # zero-copy: the XLA-shm segment view IS the prefill input
            # (row axis added on device; no host staging)
            tokens = (prompt_dev if prompt_dev.dtype == jnp.int32
                      else prompt_dev.astype(jnp.int32))[None, :]
        else:
            tokens = jnp.asarray(prompt)[None, :]
        if pos == 0:
            logits, cache = self._prefill(self._params, cache, tokens)
            pos = prompt_len
        else:
            # resumed: feed the new prompt tokens one at a time from pos
            for t in range(prompt_len):
                logits, cache = self._decode(
                    self._params, cache, tokens[:, t], pos
                )
                pos += 1

        # Software-pipelined emission: decode chunks are CHAINED on
        # device (each consumes the previous dispatch's logits/cache
        # futures), so the device→host fetch of chunk i overlaps chunk
        # i+1's compute — a remote chip's dispatch/fence round trip is
        # paid once, not per chunk.  The first token is fetched straight
        # from the prefill logits (a tiny argmax dispatched BEFORE the
        # first chunk), so time-to-first-token is prefill + one round
        # trip instead of prefill + a whole chunk.
        from collections import deque

        emitted = 0
        dispatched = 0
        inflight = deque()  # (tokens, logps, count, skip_first) device/host

        if max_tokens >= self.decode_chunk:
            # early first token: argmax of the prefill logits, dispatched
            # ahead of chunk 0 so it never waits behind chunk compute
            early_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            early_lp = jnp.take_along_axis(
                jax.nn.log_softmax(logits, axis=-1),
                early_tok[:, None], axis=-1)[:, 0]
            tokens_dev, logps_dev, logits, cache = self._decode_chunk(
                self._params, cache, logits, pos
            )
            pos += self.decode_chunk
            dispatched += self.decode_chunk
            # chunk 0's tokens[0] IS the early token; skip it on fetch
            inflight.append((tokens_dev, logps_dev,
                             self.decode_chunk - 1, True))
            t0, l0 = jax.device_get((early_tok, early_lp))
            yield self._emit_token(t0[0], l0[0], emitted, ring_write,
                                   seq_guarded)
            emitted += 1
            if eos_id is not None and int(t0[0]) == eos_id:
                if region is not None:
                    region.put_device_array(0, cache)
                return

        while emitted < max_tokens:
            # keep one chunk computing behind the one being fetched
            while dispatched < max_tokens and len(inflight) < 2:
                n = min(self.decode_chunk, max_tokens - dispatched)
                if n == self.decode_chunk:
                    tokens_dev, logps_dev, logits, cache = (
                        self._decode_chunk(
                            self._params, cache, logits, pos)
                    )
                    pos += n
                    dispatched += n
                    inflight.append((tokens_dev, logps_dev, n, False))
                else:
                    # tail shorter than the compiled chunk: per-token
                    # steps (host-driven, so values are already local)
                    tokens_host = np.empty((n,), np.int32)
                    logps_host = np.empty((n,), np.float32)
                    for i in range(n):
                        logp = jax.nn.log_softmax(logits, axis=-1)
                        token = jnp.argmax(
                            logits, axis=-1).astype(jnp.int32)
                        tokens_host[i] = int(token[0])
                        logps_host[i] = float(logp[0, tokens_host[i]])
                        if i + 1 < n or region is not None:
                            logits, cache = self._decode(
                                self._params, cache, token, pos
                            )
                            pos += 1
                    dispatched += n
                    inflight.append((tokens_host, logps_host, n, False))
            tokens_res, logps_res, n, skip_first = inflight.popleft()
            if isinstance(tokens_res, np.ndarray):
                tokens_host, logps_host = tokens_res, logps_res
            else:
                # one device->host transfer for both arrays: on remote
                # chips each fetch costs a full round trip
                tokens_all, logps_all = jax.device_get(
                    (tokens_res, logps_res))
                start = 1 if skip_first else 0
                tokens_host = tokens_all[start:, 0]
                logps_host = logps_all[start:, 0]
            for i in range(n):
                yield self._emit_token(
                    tokens_host[i], logps_host[i], emitted, ring_write,
                    seq_guarded)
                emitted += 1
                if eos_id is not None and int(tokens_host[i]) == eos_id:
                    # the EOS token is emitted, then generation stops;
                    # chunks already in flight carry tokens past EOS —
                    # the parked cache's extra rows stay masked behind
                    # the resume position, same as the scheduler's
                    # one-step retirement lag
                    if region is not None:
                        region.put_device_array(0, cache)
                    return

        if region is not None:
            # park the device-resident cache in the XLA region (zero-copy
            # in-process; host-staged cross-process).  In tp mode the
            # parked array stays sharded across the mesh.
            region.put_device_array(0, cache)

    def _execute_scheduled(self, prompt, max_tokens, eos_id, request,
                           ring_write=None, prompt_dev=None,
                           seq_guarded=False):
        """Continuous-batching path: submit to the shared decode loop and
        fan its per-step tokens back out to this stream.

        Every generation here is *resumable*: it gets an id (the
        ``generation_id`` request parameter, or a fresh uuid) and every
        response carries ``generation_id`` + a 0-based ``seq`` in its
        response parameters (SSE ``id:`` lines / gRPC response fields).
        A request carrying ``resume_generation_id`` (+
        ``resume_from_seq``, the first sequence number not yet seen)
        instead continues a parked generation: buffered tokens replay
        first, then live tokens splice in — no duplicates, no gaps.
        Resume is same-endpoint only (replay state is replica-local)."""
        import uuid

        import jax.numpy as jnp

        from tpuserver.core import RESPONSE_PARAMS_KEY
        from tpuserver.scheduler import SchedulerClosed

        scheduler = self._scheduler
        if scheduler is None:
            # close() nulled the scheduler after this request was
            # admitted: same typed outcome as racing submit into it
            raise SchedulerClosed("scheduler is shut down")

        resume_id = request.parameters.get("resume_generation_id")
        if resume_id:
            from_seq = int(request.parameters.get("resume_from_seq", 0))
            gen_id = str(resume_id)
            # the reconnect's OWN deadline governs the continuation —
            # the original request's bound died with its connection
            stream = scheduler.resume(
                gen_id, from_seq,
                deadline=getattr(request, "deadline", None))
            seq = from_seq
        else:
            region = self._kv_region(request)
            parked, pos = self._resume_state(request, region)
            # the pos+prompt+max_tokens overflow check lives in
            # DecodeScheduler.submit — one copy, same wording as this
            # class's single-stream path
            on_finish = None
            if region is not None:
                def on_finish(cache_rows):
                    # the slot's rows in the single-stream park shape,
                    # so a later request may resume on either path
                    region.put_device_array(0, cache_rows)

            gen_id = str(request.parameters.get("generation_id")
                         or uuid.uuid4().hex)
            kv_park = request.parameters.get("kv_park")
            # disaggregated phase split: a prefill-leg admission
            # (kv_phase=prefill) exports its KV when it finishes so a
            # decode replica can attach it; a decode-leg admission
            # (kv_attach=<descriptor>) imports that export and scatters
            # instead of re-prefilling (docs/resilience.md
            # "Disaggregated prefill/decode")
            kv_prefill = request.parameters.get("kv_phase") == "prefill"
            attach_cache, attach_pos = self._attach_from_params(request)
            stream = scheduler.submit(
                prompt, max_tokens, eos_id=eos_id,
                resume_cache=(jnp.asarray(parked)
                              if parked is not None else None),
                resume_pos=pos, on_finish=on_finish,
                # the deadline the core resolved (timeout parameter /
                # gRPC context): the scheduler expires pending
                # admissions before prefill and retires in-flight slots
                # past it
                deadline=getattr(request, "deadline", None),
                generation_id=gen_id,
                prompt_dev=prompt_dev,
                # park-export opt-in: the request's kv_park parameter,
                # defaulting to the model-level kv_export flag
                kv_export=(True if kv_prefill
                           else (self._kv_export if kv_park is None
                                 else bool(kv_park))),
                kv_export_on_finish=kv_prefill,
                attach_cache=attach_cache,
                attach_pos=attach_pos,
            )
            seq = 0
        for token, logprob in stream:
            if ring_write is not None:
                # the shm token ring: tensors land in the client's
                # region slot; the event shrinks to its descriptor.
                # Replayed tokens on resume REWRITE their slots (seq
                # numbering is preserved), so the router's sticky-
                # resume and handoff invariants hold unmodified.
                off = ring_write(seq, int(token), float(logprob))
                params = {"generation_id": gen_id, "seq": seq}
                params["shm_ring_offset"] = off
                event = {RESPONSE_PARAMS_KEY: params}
                if seq_guarded:
                    # seqlock lane: keep the tensors in-band too — the
                    # fallback a reader uses on a torn/stale slot
                    event["TOKEN"] = np.array([token], dtype=np.int32)
                    event["LOGPROB"] = np.array(
                        [logprob], dtype=np.float32)
                yield event
            else:
                yield {
                    "TOKEN": np.array([token], dtype=np.int32),
                    "LOGPROB": np.array([logprob], dtype=np.float32),
                    RESPONSE_PARAMS_KEY: {
                        "generation_id": gen_id, "seq": seq,
                    },
                }
            seq += 1

    def _attach_from_params(self, request):
        """``(imported cache, position)`` for a ``kv_attach``
        descriptor — the decode leg of a phase-split admission — or
        ``(None, 0)`` when the parameter is absent or the export is no
        longer importable (dropped, expired, malformed): the admission
        then runs the ordinary prefill path, token-identical, just
        slower.  The typed 404/409 edges live on the descriptor FETCH
        (``/v2/kvexport/<gid>``); by attach time the orchestrator
        already holds a claim, so degrading gracefully here is what
        makes a mid-handoff export death user-invisible."""
        desc = request.parameters.get("kv_attach")
        if not desc or self._server is None:
            return None, 0
        if isinstance(desc, (bytes, str)):
            import json

            try:
                desc = json.loads(desc)
            except ValueError:
                return None, 0
        from tpuserver.errors import KvExportNotFound

        try:
            return self._server.import_kv_descriptor(desc)
        except KvExportNotFound:
            return None, 0

    def healthy(self):
        """Readiness probe hook: False once the decode loop tripped
        permanently (restart budget exhausted) or the scheduler is
        closed (``InferenceServer.server_ready``/``model_ready`` report
        it).  Bound once: a concurrent close() nulls ``_scheduler``
        between reads."""
        scheduler = self._scheduler
        return scheduler is None or scheduler.healthy

    def scheduler_stats(self):
        """The decode scheduler's ``stats()`` dict (restart and
        quarantine counters ops alert on), or None before first use /
        in single-stream mode."""
        scheduler = self._scheduler
        return scheduler.stats() if scheduler is not None else None

    def drain(self, timeout=30.0):
        """Stop admission and let in-flight generations finish within
        ``timeout`` seconds (called by ``InferenceServer.drain``);
        no-op for max_slots=1."""
        scheduler = self._scheduler
        if scheduler is not None:
            scheduler.drain(timeout)

    def close(self):
        """Stop the continuous-batching loop (no-op for max_slots=1).
        Called by ``InferenceServer.close``.  Compiled state is reset so
        a server re-opened by a later frontend attach rebuilds a FRESH
        scheduler on the next request instead of failing every
        generation against the closed one forever."""
        if self._scheduler is not None:
            self._scheduler.close()
            with self._lock:
                self._scheduler = None
                self._params = None
