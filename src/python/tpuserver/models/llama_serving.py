"""Decoupled llama generation serving model (BASELINE config #5: token-by-
token generate streaming with TPU-shm KV handles).

One request carries the prompt ids; the model prefillls the KV cache in one
batched pass, then streams one sampled token per response over the
decoupled channel (ModelStreamInfer).  Generation runs as a jitted
decode_step per token — static shapes, cache donated, so steady-state cost
is one device dispatch per token.

KV-cache persistence: a request parameter ``kv_cache_region`` naming a
registered XLA shared-memory region makes the model park the finished KV
cache (a device-resident ``jax.Array``) in that region and, on a follow-up
request with the same parameter and ``kv_cache_resume=True``, continue
generation from it without re-prefilling — the TPU-shm analogue of the
reference's CUDA-shm tensor passing, applied to generation state.
"""

import threading

import numpy as np

from tpuserver.core import Model, TensorSpec
from tpuserver.models import llama


class LlamaGenerateModel(Model):
    """PROMPT_IDS int32[-1], MAX_TOKENS int32[1] -> stream of
    (TOKEN int32[1], LOGPROB fp32[1]) responses."""

    name = "llama_generate"
    platform = "jax"
    backend = "jax"
    max_batch_size = 0
    decoupled = True
    inputs = (
        TensorSpec("PROMPT_IDS", "INT32", [-1]),
        TensorSpec("MAX_TOKENS", "INT32", [1]),
    )
    outputs = (
        TensorSpec("TOKEN", "INT32", [1]),
        TensorSpec("LOGPROB", "FP32", [1]),
    )

    def __init__(self, cfg=None, max_seq=512, server=None):
        self._cfg = cfg or llama.tiny(vocab=2048)
        self._max_seq = max_seq
        self._server = server  # for kv_cache_region xla-shm lookups
        self._params = None
        self._prefill = None
        self._decode = None
        self._lock = threading.Lock()

    def attach_server(self, server):
        self._server = server

    def _ensure_compiled(self):
        if self._decode is not None:
            return
        with self._lock:
            if self._decode is None:
                import functools

                import jax

                self._params = llama.init_params(
                    jax.random.PRNGKey(0), self._cfg
                )
                self._prefill = jax.jit(
                    functools.partial(llama.prefill, cfg=self._cfg)
                )
                self._decode = jax.jit(
                    functools.partial(llama.decode_step, cfg=self._cfg),
                    donate_argnums=(1,),
                )

    def warmup(self):
        self._ensure_compiled()

    def _kv_region(self, request):
        from tpuserver.core import ServerError

        name = request.parameters.get("kv_cache_region")
        if not name:
            return None
        if self._server is None:
            raise ServerError(
                "model '{}' has no server attached; kv_cache_region "
                "requires a registered XLA shm region".format(self.name)
            )
        return self._server.xla_shm_region(name)

    def execute_stream(self, inputs, request):
        import jax
        import jax.numpy as jnp

        self._ensure_compiled()
        prompt = np.asarray(inputs["PROMPT_IDS"]).reshape(-1).astype(np.int32)
        max_tokens = int(np.asarray(inputs["MAX_TOKENS"]).reshape(-1)[0])
        if len(prompt) == 0:
            raise ValueError("PROMPT_IDS must be non-empty")

        region = self._kv_region(request)
        resume = bool(request.parameters.get("kv_cache_resume")) and (
            region is not None
        )
        pos = 0
        cache = None
        if resume:
            parked = region.handle.get_jax_segment(0)
            if parked is not None:
                if "kv_cache_position" not in request.parameters:
                    raise ValueError(
                        "kv_cache_resume requires kv_cache_position (the "
                        "sequence position the parked cache was left at)"
                    )
                # decode_step donates its cache argument; copy so the parked
                # array in the region registry stays valid even if this
                # stream dies mid-generation.
                cache = jnp.copy(parked)
                pos = int(request.parameters["kv_cache_position"])
        if cache is None:
            cache = llama.init_kv_cache(self._cfg, 1, self._max_seq)
            pos = 0
        if pos + len(prompt) + max_tokens > self._max_seq:
            raise ValueError(
                "position ({}) + prompt ({}) + max_tokens ({}) exceeds max "
                "sequence {}".format(
                    pos, len(prompt), max_tokens, self._max_seq
                )
            )

        tokens = jnp.asarray(prompt)[None, :]
        if pos == 0:
            logits, cache = self._prefill(self._params, cache, tokens)
            pos = len(prompt)
        else:
            # resumed: feed the new prompt tokens one at a time from pos
            for t in range(len(prompt)):
                logits, cache = self._decode(
                    self._params, cache, tokens[:, t], pos
                )
                pos += 1

        for i in range(max_tokens):
            logp = jax.nn.log_softmax(logits, axis=-1)
            token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            token_id = int(token[0])
            yield {
                "TOKEN": np.array([token_id], dtype=np.int32),
                "LOGPROB": np.array(
                    [float(logp[0, token_id])], dtype=np.float32
                ),
            }
            # the trailing decode only matters if another token follows or
            # the cache is being parked for resumption
            if i + 1 < max_tokens or region is not None:
                logits, cache = self._decode(
                    self._params, cache, token, pos
                )
                pos += 1

        if region is not None:
            # park the device-resident cache in the XLA region (zero-copy
            # in-process; host-staged cross-process)
            region.put_device_array(0, cache)
