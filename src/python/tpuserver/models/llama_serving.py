"""Decoupled llama generation serving model (BASELINE config #5: token-by-
token generate streaming with TPU-shm KV handles).

One request carries the prompt ids; the model prefillls the KV cache in one
batched pass, then streams one sampled token per response over the
decoupled channel (ModelStreamInfer).  Generation runs as a jitted
decode_step per token — static shapes, cache donated, so steady-state cost
is one device dispatch per token.

KV-cache persistence: a request parameter ``kv_cache_region`` naming a
registered XLA shared-memory region makes the model park the finished KV
cache (a device-resident ``jax.Array``) in that region and, on a follow-up
request with the same parameter and ``kv_cache_resume=True``, continue
generation from it without re-prefilling — the TPU-shm analogue of the
reference's CUDA-shm tensor passing, applied to generation state.
"""

import threading

import numpy as np

from tpuserver.core import Model, TensorSpec
from tpuserver.models import llama


class LlamaGenerateModel(Model):
    """PROMPT_IDS int32[-1], MAX_TOKENS int32[1] -> stream of
    (TOKEN int32[1], LOGPROB fp32[1]) responses."""

    name = "llama_generate"
    platform = "jax"
    backend = "jax"
    max_batch_size = 0
    decoupled = True
    inputs = (
        TensorSpec("PROMPT_IDS", "INT32", [-1]),
        TensorSpec("MAX_TOKENS", "INT32", [1]),
    )
    outputs = (
        TensorSpec("TOKEN", "INT32", [1]),
        TensorSpec("LOGPROB", "FP32", [1]),
    )

    # tokens greedy-decoded per device dispatch: the steady state is
    # dispatch-latency-bound on remote chips, so a scanned chunk
    # amortizes the host<->device hop over several tokens (each token
    # still streams as its own decoupled response)
    decode_chunk = 8

    def __init__(self, cfg=None, max_seq=512, server=None,
                 decode_chunk=None):
        self._cfg = cfg or llama.tiny(vocab=2048)
        self._max_seq = max_seq
        self._server = server  # for kv_cache_region xla-shm lookups
        self._params = None
        self._prefill = None
        self._decode = None
        self._decode_chunk = None
        if decode_chunk is not None:
            if decode_chunk < 1:
                raise ValueError(
                    "decode_chunk must be >= 1 (got {})".format(
                        decode_chunk))
            self.decode_chunk = decode_chunk
        self._lock = threading.Lock()

    def attach_server(self, server):
        self._server = server

    def _ensure_compiled(self):
        if self._decode is not None:
            return
        with self._lock:
            if self._decode is None:
                import functools

                import jax

                self._params = llama.init_params(
                    jax.random.PRNGKey(0), self._cfg
                )
                self._prefill = jax.jit(
                    functools.partial(llama.prefill, cfg=self._cfg)
                )
                self._decode = jax.jit(
                    functools.partial(llama.decode_step, cfg=self._cfg),
                    donate_argnums=(1,),
                )
                self._decode_chunk = jax.jit(
                    functools.partial(
                        llama.decode_chunk, cfg=self._cfg,
                        chunk=self.decode_chunk),
                    donate_argnums=(1,),
                )

    def warmup(self):
        self._ensure_compiled()

    def _kv_region(self, request):
        from tpuserver.core import ServerError

        name = request.parameters.get("kv_cache_region")
        if not name:
            return None
        if self._server is None:
            raise ServerError(
                "model '{}' has no server attached; kv_cache_region "
                "requires a registered XLA shm region".format(self.name)
            )
        return self._server.xla_shm_region(name)

    def execute_stream(self, inputs, request):
        import jax
        import jax.numpy as jnp

        self._ensure_compiled()
        prompt = np.asarray(inputs["PROMPT_IDS"]).reshape(-1).astype(np.int32)
        max_tokens = int(np.asarray(inputs["MAX_TOKENS"]).reshape(-1)[0])
        if len(prompt) == 0:
            raise ValueError("PROMPT_IDS must be non-empty")

        region = self._kv_region(request)
        resume = bool(request.parameters.get("kv_cache_resume")) and (
            region is not None
        )
        pos = 0
        cache = None
        if resume:
            parked = region.handle.get_jax_segment(0)
            if parked is not None:
                if "kv_cache_position" not in request.parameters:
                    raise ValueError(
                        "kv_cache_resume requires kv_cache_position (the "
                        "sequence position the parked cache was left at)"
                    )
                # decode_step donates its cache argument; copy so the parked
                # array in the region registry stays valid even if this
                # stream dies mid-generation.
                cache = jnp.copy(parked)
                pos = int(request.parameters["kv_cache_position"])
        if cache is None:
            cache = llama.init_kv_cache(self._cfg, 1, self._max_seq)
            pos = 0
        if pos + len(prompt) + max_tokens > self._max_seq:
            raise ValueError(
                "position ({}) + prompt ({}) + max_tokens ({}) exceeds max "
                "sequence {}".format(
                    pos, len(prompt), max_tokens, self._max_seq
                )
            )

        tokens = jnp.asarray(prompt)[None, :]
        if pos == 0:
            logits, cache = self._prefill(self._params, cache, tokens)
            pos = len(prompt)
        else:
            # resumed: feed the new prompt tokens one at a time from pos
            for t in range(len(prompt)):
                logits, cache = self._decode(
                    self._params, cache, tokens[:, t], pos
                )
                pos += 1

        emitted = 0
        while emitted < max_tokens:
            n = min(self.decode_chunk, max_tokens - emitted)
            if n == self.decode_chunk:
                # full chunk: one dispatch greedy-decodes chunk tokens
                tokens_dev, logps_dev, logits, cache = self._decode_chunk(
                    self._params, cache, logits, pos
                )
                # one device->host transfer for both arrays: on remote
                # chips each fetch costs a full round trip
                tokens_all, logps_all = jax.device_get(
                    (tokens_dev, logps_dev))
                tokens_host = tokens_all[:, 0]
                logps_host = logps_all[:, 0]
                pos += n
            else:
                # tail shorter than the compiled chunk: per-token steps
                tokens_host = np.empty((n,), np.int32)
                logps_host = np.empty((n,), np.float32)
                for i in range(n):
                    logp = jax.nn.log_softmax(logits, axis=-1)
                    token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    tokens_host[i] = int(token[0])
                    logps_host[i] = float(logp[0, tokens_host[i]])
                    if i + 1 < n or region is not None:
                        logits, cache = self._decode(
                            self._params, cache, token, pos
                        )
                        pos += 1
            for i in range(n):
                yield {
                    "TOKEN": np.array([tokens_host[i]], dtype=np.int32),
                    "LOGPROB": np.array([logps_host[i]], dtype=np.float32),
                }
            emitted += n

        if region is not None:
            # park the device-resident cache in the XLA region (zero-copy
            # in-process; host-staged cross-process)
            region.put_device_array(0, cache)
