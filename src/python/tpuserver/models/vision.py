"""Vision model family: ResNet-50 and DenseNet-121 in pure JAX, TPU-first.

These serve BASELINE configs #2/#3 (the reference drives ResNet-50 /
DenseNet-121 through image_client / shm examples; reference
src/c++/examples/image_client.cc:64-120).  Layout is NHWC (TPU native),
compute dtype bfloat16 with fp32 accumulation in XLA's conv/matmul, batch
norm folded to inference-mode scale/shift.  Weights are randomly
initialized — the framework benches protocol + data-plane + device
round-trip, not ImageNet accuracy.
"""

import threading

import numpy as np

from tpuserver.core import JaxModel, Model, TensorSpec


def _conv(x, w, stride=1, padding="SAME"):
    import jax.numpy as jnp
    from jax import lax

    return lax.conv_general_dilated(
        x, w,
        window_strides=(stride, stride),
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32,
    ).astype(x.dtype)


def _scale_shift(x, scale, shift):
    # inference-mode batch norm folded into one multiply-add (fused by XLA)
    return x * scale + shift


def _conv_w(key, kh, kw, cin, cout):
    import jax
    import jax.numpy as jnp

    fan_in = kh * kw * cin
    return (
        jax.random.normal(key, (kh, kw, cin, cout), jnp.float32)
        * np.sqrt(2.0 / fan_in)
    ).astype(jnp.bfloat16)


def _bn(c):
    import jax.numpy as jnp

    return {
        "scale": jnp.ones((c,), jnp.bfloat16),
        "shift": jnp.zeros((c,), jnp.bfloat16),
    }


def _stem(params, x):
    """Shared 7x7/2 conv stem + 3x3/2 max pool."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    x = _conv(x, params["stem"]["w"], stride=2)
    x = jax.nn.relu(_scale_shift(x, params["stem"]["bn"]["scale"],
                                 params["stem"]["bn"]["shift"]))
    return lax.reduce_window(
        x, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
    )


class _ImageNetModel(JaxModel):
    """Shared plumbing: bf16 params, NHWC [B,224,224,3] fp32 wire input,
    softmax probabilities [B,1000] out, classification labels."""

    max_batch_size = 32
    # coalesce concurrent b1 requests into one MXU-shaped dispatch: a
    # conv net at batch 1 leaves the systolic array mostly idle, and on
    # a remote chip each extra dispatch costs a full host<->device hop.
    # Power-of-two buckets (the batcher default) keep the padding tax
    # under 2x while bounding the compiled-shape set; compiles persist
    # across runs via the XLA compilation cache.
    dynamic_batching = True
    # overlapping executors hide the ~100 ms tunnel sync of one batch
    # behind the next batch's compute (instance_group count analogue)
    instance_count = 4
    inputs = (TensorSpec("INPUT", "FP32", [224, 224, 3]),)
    outputs = (TensorSpec("OUTPUT", "FP32", [1000]),)

    def __init__(self, seed=0):
        super().__init__()
        self._params = None
        self._seed = seed
        self._params_lock = threading.Lock()
        self.labels = {
            "OUTPUT": ["class_{}".format(i) for i in range(1000)]
        }

    def prepare(self):
        # eager param init (outside any jit trace; see JaxModel.prepare)
        self._get_params()

    def _get_params(self):
        if self._params is None:
            with self._params_lock:
                if self._params is None:
                    self._params = self._init_params()
        return self._params

    def jax_fn(self, INPUT):
        import jax
        import jax.numpy as jnp

        params = self._get_params()
        x = INPUT.astype(jnp.bfloat16)
        logits = self._apply(params, x)
        return {
            "OUTPUT": jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        }

    def warmup(self):
        import numpy as np

        # compile every batch shape live traffic can run at: the
        # batcher's buckets (declared, else its power-of-two default)
        # plus batch 1 (parameter-carrying requests bypass the batcher).
        # A cold shape is a multi-minute conv-net compile landing inside
        # somebody's request; warmed compiles persist in the XLA cache.
        buckets = self.batch_buckets
        if buckets is None and self.dynamic_batching:
            buckets, b = [], 1
            while b < self.max_batch_size:
                buckets.append(b)
                b <<= 1
            buckets.append(self.max_batch_size)
        for b in {1, *(buckets or ())}:
            self.execute(
                {"INPUT": np.zeros((b, 224, 224, 3), np.float32)}, None
            )


class ResNet50Model(_ImageNetModel):
    """ResNet-50 v1.5 (stride-2 in the 3x3 of downsampling bottlenecks).

    Stage plan (3, 4, 6, 3) bottlenecks — the standard 50-layer graph the
    reference benches over TF-Serving/TorchServe (docs/benchmarking.md:121).
    """

    name = "resnet50"
    platform = "jax"
    backend = "jax"

    _STAGES = (3, 4, 6, 3)
    _WIDTHS = (256, 512, 1024, 2048)

    def _init_params(self):
        import jax
        import jax.numpy as jnp

        key = jax.random.PRNGKey(self._seed)
        conv_w, bn = _conv_w, _bn

        keys = iter(jax.random.split(key, 200))
        params = {
            "stem": {"w": conv_w(next(keys), 7, 7, 3, 64), "bn": bn(64)},
            "stages": [],
        }
        cin = 64
        for stage, (blocks, width) in enumerate(
            zip(self._STAGES, self._WIDTHS)
        ):
            mid = width // 4
            stage_params = []
            for b in range(blocks):
                blk = {
                    "w1": conv_w(next(keys), 1, 1, cin, mid),
                    "bn1": bn(mid),
                    "w2": conv_w(next(keys), 3, 3, mid, mid),
                    "bn2": bn(mid),
                    "w3": conv_w(next(keys), 1, 1, mid, width),
                    "bn3": bn(width),
                }
                if b == 0:
                    blk["proj"] = conv_w(next(keys), 1, 1, cin, width)
                    blk["proj_bn"] = bn(width)
                stage_params.append(blk)
                cin = width
            params["stages"].append(stage_params)
        params["fc"] = {
            "w": (
                jax.random.normal(
                    next(keys), (2048, 1000), jnp.float32
                ) * 0.01
            ).astype(jnp.bfloat16),
            "b": jnp.zeros((1000,), jnp.bfloat16),
        }
        return params

    def _apply(self, params, x):
        import jax
        import jax.numpy as jnp
        from jax import lax

        relu = jax.nn.relu
        x = _stem(params, x)
        for stage, stage_params in enumerate(params["stages"]):
            for b, blk in enumerate(stage_params):
                stride = 2 if (b == 0 and stage > 0) else 1
                shortcut = x
                if "proj" in blk:
                    shortcut = _conv(x, blk["proj"], stride=stride)
                    shortcut = _scale_shift(
                        shortcut, blk["proj_bn"]["scale"],
                        blk["proj_bn"]["shift"],
                    )
                y = relu(_scale_shift(
                    _conv(x, blk["w1"]), blk["bn1"]["scale"],
                    blk["bn1"]["shift"],
                ))
                y = relu(_scale_shift(
                    _conv(y, blk["w2"], stride=stride),
                    blk["bn2"]["scale"], blk["bn2"]["shift"],
                ))
                y = _scale_shift(
                    _conv(y, blk["w3"]), blk["bn3"]["scale"],
                    blk["bn3"]["shift"],
                )
                x = relu(y + shortcut)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        return x @ params["fc"]["w"] + params["fc"]["b"]


class DenseNet121Model(_ImageNetModel):
    """DenseNet-121: dense blocks (6, 12, 24, 16), growth rate 32,
    transition compression 0.5 (BASELINE config #3's model)."""

    name = "densenet121"
    platform = "jax"
    backend = "jax"

    _BLOCKS = (6, 12, 24, 16)
    _GROWTH = 32

    def _init_params(self):
        import jax
        import jax.numpy as jnp

        key = jax.random.PRNGKey(self._seed)
        conv_w, bn = _conv_w, _bn

        keys = iter(jax.random.split(key, 400))
        params = {
            "stem": {"w": conv_w(next(keys), 7, 7, 3, 64), "bn": bn(64)},
            "blocks": [],
            "transitions": [],
        }
        c = 64
        for i, layers in enumerate(self._BLOCKS):
            block = []
            for _ in range(layers):
                block.append(
                    {
                        "bn1": bn(c),
                        "w1": conv_w(next(keys), 1, 1, c, 4 * self._GROWTH),
                        "bn2": bn(4 * self._GROWTH),
                        "w2": conv_w(
                            next(keys), 3, 3, 4 * self._GROWTH, self._GROWTH
                        ),
                    }
                )
                c += self._GROWTH
            params["blocks"].append(block)
            if i < len(self._BLOCKS) - 1:
                cout = c // 2
                params["transitions"].append(
                    {"bn": bn(c), "w": conv_w(next(keys), 1, 1, c, cout)}
                )
                c = cout
        params["final_bn"] = bn(c)
        params["fc"] = {
            "w": (
                jax.random.normal(next(keys), (c, 1000), jnp.float32) * 0.01
            ).astype(jnp.bfloat16),
            "b": jnp.zeros((1000,), jnp.bfloat16),
        }
        return params

    def _apply(self, params, x):
        import jax
        import jax.numpy as jnp
        from jax import lax

        relu = jax.nn.relu
        x = _stem(params, x)
        for i, block in enumerate(params["blocks"]):
            for layer in block:
                y = relu(_scale_shift(
                    x, layer["bn1"]["scale"], layer["bn1"]["shift"]
                ))
                y = _conv(y, layer["w1"])
                y = relu(_scale_shift(
                    y, layer["bn2"]["scale"], layer["bn2"]["shift"]
                ))
                y = _conv(y, layer["w2"])
                x = jnp.concatenate([x, y], axis=-1)
            if i < len(params["transitions"]):
                tr = params["transitions"][i]
                x = relu(_scale_shift(
                    x, tr["bn"]["scale"], tr["bn"]["shift"]
                ))
                x = _conv(x, tr["w"])
                x = lax.reduce_window(
                    x, 0.0, lax.add, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
                ) / 4.0
        x = relu(_scale_shift(
            x, params["final_bn"]["scale"], params["final_bn"]["shift"]
        ))
        x = jnp.mean(x, axis=(1, 2))
        return x @ params["fc"]["w"] + params["fc"]["b"]


class ImagePreprocessModel(JaxModel):
    """Raw UINT8 pixels -> normalized FP32 network input.

    The preprocessing stage the reference's ensemble_image_client drives as
    the first composing model of its image ensemble (reference
    src/c++/examples/ensemble_image_client.cc); here it is a jitted cast +
    scale so the whole ensemble stays on device.
    """

    name = "image_preprocess"
    platform = "jax"
    backend = "jax"
    max_batch_size = 32
    inputs = (TensorSpec("RAW_IMAGE", "UINT8", [224, 224, 3]),)
    outputs = (TensorSpec("PREPROCESSED", "FP32", [224, 224, 3]),)

    def jax_fn(self, RAW_IMAGE):
        import jax.numpy as jnp

        return {
            "PREPROCESSED": RAW_IMAGE.astype(jnp.float32) / 255.0
        }


class ImageEnsembleModel(Model):
    """RAW_IMAGE -> classification probs via preprocess + ResNet-50
    (ensemble_scheduling; role of the reference's preprocess+classifier
    ensemble in ensemble_image_client.cc).  Plain Model like
    BertEnsembleModel: the core's ensemble dispatch runs the steps, so no
    jit machinery of its own."""

    name = "image_ensemble"
    platform = "ensemble"
    backend = ""
    max_batch_size = 32
    inputs = (TensorSpec("RAW_IMAGE", "UINT8", [224, 224, 3]),)
    outputs = (TensorSpec("OUTPUT", "FP32", [1000]),)
    ensemble_steps = [
        {
            "model_name": "image_preprocess",
            "model_version": -1,
            "input_map": {"RAW_IMAGE": "RAW_IMAGE"},
            "output_map": {"PREPROCESSED": "pixels"},
        },
        {
            "model_name": "resnet50",
            "model_version": -1,
            "input_map": {"INPUT": "pixels"},
            "output_map": {"OUTPUT": "OUTPUT"},
        },
    ]

    def __init__(self):
        super().__init__()
        self.labels = {
            "OUTPUT": ["class_{}".format(i) for i in range(1000)]
        }
