"""Built-in model zoo for tpuserver.

These play the role of the quick-start / QA models the reference's examples
and tests run against (``simple`` add/sub, identity models, image
classifiers, ensembles, sequence and decoupled models) — implemented as
jitted JAX computations.
"""

from tpuserver.models.simple import (
    IdentityBF16Model,
    IdentityFP32Model,
    IdentityStringModel,
    RepeatModel,
    SequenceAccumulateModel,
    SimpleModel,
    SimpleStringModel,
)


def default_models():
    """The standard test-fixture model set."""
    return [
        SimpleModel(),
        SimpleStringModel(),
        IdentityFP32Model(),
        IdentityBF16Model(),
        IdentityStringModel(),
        SequenceAccumulateModel(),
        RepeatModel(),
    ]
