"""Built-in model zoo for tpuserver.

These play the role of the quick-start / QA models the reference's examples
and tests run against (``simple`` add/sub, identity models, image
classifiers, ensembles, sequence and decoupled models) — implemented as
jitted JAX computations.
"""

from tpuserver.models.simple import (
    DelayedIdentityModel,
    IdentityBF16Model,
    IdentityFP32Model,
    IdentityStringModel,
    RepeatModel,
    SequenceAccumulateModel,
    SimpleModel,
    SimpleStringModel,
)


def default_models():
    """The standard test-fixture model set."""
    return [
        SimpleModel(),
        SimpleStringModel(),
        IdentityFP32Model(),
        IdentityBF16Model(),
        IdentityStringModel(),
        DelayedIdentityModel(),
        SequenceAccumulateModel(),
        RepeatModel(),
    ]


def serving_models(include_vision=True, include_bert=True,
                   include_llama=True, llama_cfg=None,
                   llama_decode_chunk=None, llama_max_seq=512,
                   llama_mesh=None, llama_quantize=False,
                   llama_max_slots=1):
    """The heavyweight serving zoo for the BASELINE configs (#2-#5):
    ResNet-50 / DenseNet-121, the BERT ensemble, and decoupled llama
    generation.  Separate from ``default_models`` so unit tests stay fast.

    ``llama_max_slots > 1`` turns on the continuous-batching decode
    scheduler: that many concurrent generations share one slotted KV
    cache and every decode step serves them all in a single dispatch."""
    models = []
    if include_vision:
        from tpuserver.models.vision import (
            DenseNet121Model,
            ImageEnsembleModel,
            ImagePreprocessModel,
            ResNet50Model,
        )

        models += [ResNet50Model(), DenseNet121Model(),
                   ImagePreprocessModel(), ImageEnsembleModel()]
    if include_bert:
        from tpuserver.models.bert import (
            BertEncoderModel,
            BertEnsembleModel,
            BertTokenizerModel,
        )

        models += [BertTokenizerModel(), BertEncoderModel(),
                   BertEnsembleModel()]
    if include_llama:
        from tpuserver.models.llama_serving import LlamaGenerateModel

        models.append(LlamaGenerateModel(
            cfg=llama_cfg, max_seq=llama_max_seq,
            decode_chunk=llama_decode_chunk,
            mesh=llama_mesh, quantize=llama_quantize,
            max_slots=llama_max_slots))
    return models
