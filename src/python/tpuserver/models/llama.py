"""Llama-family decoder-only transformer, TPU-first.

The flagship compute graph behind BASELINE config #5 ("Llama-3-8B decoupled
streaming").  This is NOT a torch port: parameters are a plain pytree of
``jnp.bfloat16`` arrays, the forward pass is pure einsum (MXU-shaped), all
control flow is static or ``lax``-level, and scale-out is expressed only as
``NamedSharding`` rules over a (dp, sp, tp) mesh — XLA inserts the
collectives.  Long context runs as a ``shard_map`` ring-attention program
over the ``sp`` axis (tpuserver/parallel/ring.py).

Pieces:
- ``LlamaConfig`` presets (``tiny`` test size → ``llama3_8b``)
- ``init_params`` / ``param_specs`` (Megatron column/row tp split)
- ``forward`` (teacher-forcing logits; dense or ring attention)
- ``train_step`` factory (cross-entropy + optax adamw) for the multi-chip
  dry-run
- ``init_kv_cache`` / ``decode_step`` / ``prefill`` for token-by-token
  serving (decoupled streaming)
"""

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from tpuserver.parallel.ring import ring_attention
from tpuserver.parallel.ulysses import ulysses_attention


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab: int = 128256
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: object = jnp.bfloat16
    # sequence-parallel attention: "ring" (ppermute K/V rotation — scales
    # to any head count) or "ulysses" (two all_to_alls, full-sequence
    # attention per head shard — needs local heads divisible by sp)
    sp_strategy: str = "ring"
    # single-shard prefill/forward attention: "xla" (compiler-fused
    # dense) or "pallas" (the hand-tiled flash kernel,
    # tpuserver.ops.flash_attention; needs T divisible by its block
    # sizes, falling back to dense otherwise).  Measured on v5e at
    # T=2048 on the 3B preset: flash (bf16 operands, 256x512 tiles)
    # prefills at 55% MFU vs 39% dense — see docs/benchmarking.md.
    # The real-size presets default to "pallas"; "xla" here keeps the
    # tiny test config on the portable dense path.
    attn_impl: str = "xla"
    # single-query decode attention: "auto" (default), "xla" or
    # "pallas" (tpuserver.ops.decode_attention).  The Pallas kernel
    # skips dead cache-tail blocks, winning up to ~10x when the valid
    # prefix is a small fraction of max_seq; XLA's fused dense wins for
    # short, mostly-full caches.  "auto" picks STATICALLY at trace time
    # from the measured cost model (docs/benchmarking.md): the kernel
    # when it wins for the majority of possible cache lengths, dense
    # otherwise.  (A per-step lax.cond was measured and rejected: XLA
    # cannot alias the KV cache through cond branches, and the copies
    # collapsed long-context decode 3x — see bench_prefill_sweep.)
    decode_impl: str = "auto"
    # flash-kernel tile sizes (prefill): preferred tiles, tuned on v5e
    # via tools/bench_prefill_sweep.py (256x512 = 55% MFU on the 3B at
    # T=2048 vs 44% at 128x128); prompts not divisible by these fall
    # back to 128-tiles, then to the dense path (_flash_blocks)
    flash_block_q: int = 256
    flash_block_k: int = 512

    @property
    def head_dim(self):
        return self.d_model // self.n_heads


def llama3_8b():
    return LlamaConfig(attn_impl="pallas")


def llama3_3b():
    """Llama-3.2-3B shapes (untied head): ~3.6B params ≈ 7.2 GB bf16 —
    the largest preset that fits a single v5e chip's 16 GB HBM with KV
    cache and compiler workspace to spare (the 8B preset's 16 GB of
    weights alone would not).  The single-chip serving flagship."""
    return LlamaConfig(
        d_model=3072, n_layers=28, n_heads=24, n_kv_heads=8, d_ff=8192,
        attn_impl="pallas",
    )


def llama3_1b():
    """Llama-3.2-1B shapes (untied head): ~1.5B params ≈ 3 GB bf16."""
    return LlamaConfig(
        d_model=2048, n_layers=16, n_heads=32, n_kv_heads=8, d_ff=8192,
        attn_impl="pallas",
    )


def tiny(vocab=256):
    """Test-size config: same graph, toy dims (multiples of 8 for sharding)."""
    return LlamaConfig(
        vocab=vocab, d_model=64, n_layers=2, n_heads=8, n_kv_heads=4,
        d_ff=128, rope_theta=10000.0,
    )


# -- parameters --------------------------------------------------------------


def init_params(key, cfg):
    """Pytree of bf16 params: {embed, layers: [..], norm}."""
    k_embed, k_out, *k_layers = jax.random.split(key, 2 + cfg.n_layers)
    hd = cfg.head_dim

    def dense(k, shape, fan_in):
        return (
            jax.random.normal(k, shape, jnp.float32) / np.sqrt(fan_in)
        ).astype(cfg.dtype)

    layers = []
    for kl in k_layers:
        ks = jax.random.split(kl, 7)
        layers.append(
            {
                "attn_norm": jnp.ones((cfg.d_model,), cfg.dtype),
                "wq": dense(ks[0], (cfg.d_model, cfg.n_heads * hd),
                            cfg.d_model),
                "wk": dense(ks[1], (cfg.d_model, cfg.n_kv_heads * hd),
                            cfg.d_model),
                "wv": dense(ks[2], (cfg.d_model, cfg.n_kv_heads * hd),
                            cfg.d_model),
                "wo": dense(ks[3], (cfg.n_heads * hd, cfg.d_model),
                            cfg.n_heads * hd),
                "mlp_norm": jnp.ones((cfg.d_model,), cfg.dtype),
                "w_gate": dense(ks[4], (cfg.d_model, cfg.d_ff), cfg.d_model),
                "w_up": dense(ks[5], (cfg.d_model, cfg.d_ff), cfg.d_model),
                "w_down": dense(ks[6], (cfg.d_ff, cfg.d_model), cfg.d_ff),
            }
        )
    return {
        "embed": dense(k_embed, (cfg.vocab, cfg.d_model), cfg.d_model),
        "layers": layers,
        "norm": jnp.ones((cfg.d_model,), cfg.dtype),
        "lm_head": dense(k_out, (cfg.d_model, cfg.vocab), cfg.d_model),
    }


def param_specs(cfg, quantized=False, quantized_embed=False):
    """PartitionSpec pytree: Megatron split — qkv/gate/up column-parallel on
    tp, o/down row-parallel; embeddings sharded on vocab.

    With ``quantized=True`` the specs match the ``quantize_params`` tree:
    each int8 weight keeps its bf16 spec and its per-output-channel scale
    vector shards along the weight's sharded *output* dim (replicated for
    row-parallel weights, whose outputs are unsharded).  Pass
    ``quantized_embed=True`` iff ``quantize_params`` ran with
    ``quantize_embed=True`` (its per-ROW scales shard with the vocab
    rows)."""

    def wspec(spec, out_axis_name):
        if not quantized:
            return spec
        return {"q": spec, "s": P(out_axis_name)}

    layer = {
        "attn_norm": P(),
        "wq": wspec(P(None, "tp"), "tp"),
        "wk": wspec(P(None, "tp"), "tp"),
        "wv": wspec(P(None, "tp"), "tp"),
        "wo": wspec(P("tp", None), None),
        "mlp_norm": P(),
        "w_gate": wspec(P(None, "tp"), "tp"),
        "w_up": wspec(P(None, "tp"), "tp"),
        "w_down": wspec(P("tp", None), None),
    }
    return {
        "embed": (
            {"q": P("tp", None), "s": P("tp")}
            if quantized and quantized_embed
            else P("tp", None)
        ),
        "layers": [
            {k: (dict(v) if isinstance(v, dict) else v)
             for k, v in layer.items()}
            for _ in range(cfg.n_layers)
        ],
        "norm": P(),
        "lm_head": wspec(P(None, "tp"), "tp"),
    }


def quantize_params(params, quantize_embed=False):
    """Int8-quantize the serving weights (per-output-channel scales).

    Layer matmul weights and ``lm_head`` go int8 (~2x HBM shrink — what
    fits the 8B preset's 16 GB of bf16 weights into a single v5e);
    norms stay bf16.  ``embed`` is a row gather, not a matmul; it stays
    bf16 by default for exact lookups (pass ``quantize_embed=True`` to
    shrink it too).
    """
    from tpuserver.ops import quant

    out = {
        "embed": (
            quant.quantize_int8(params["embed"], axis=1)
            if quantize_embed
            else params["embed"]
        ),
        "norm": params["norm"],
        "lm_head": quant.quantize_int8(params["lm_head"], axis=0),
        "layers": [],
    }
    for layer in params["layers"]:
        out["layers"].append(
            {
                "attn_norm": layer["attn_norm"],
                "mlp_norm": layer["mlp_norm"],
                "wq": quant.quantize_int8(layer["wq"], axis=0),
                "wk": quant.quantize_int8(layer["wk"], axis=0),
                "wv": quant.quantize_int8(layer["wv"], axis=0),
                "wo": quant.quantize_int8(layer["wo"], axis=0),
                "w_gate": quant.quantize_int8(layer["w_gate"], axis=0),
                "w_up": quant.quantize_int8(layer["w_up"], axis=0),
                "w_down": quant.quantize_int8(layer["w_down"], axis=0),
            }
        )
    return out


# -- kernels -----------------------------------------------------------------


def _flash_blocks(T, cfg):
    """Largest usable (block_q, block_k) for a length-T flash prefill:
    the preferred (tuned) tile when T divides by it, else 128-tiles,
    else None (caller falls back to dense attention)."""
    bq = next(
        (b for b in (cfg.flash_block_q, 128) if b <= T and T % b == 0),
        None,
    )
    bk = next(
        (b for b in (cfg.flash_block_k, 256, 128)
         if b <= T and T % b == 0),
        None,
    )
    return bq, bk


def _mm(x, w):
    """Matmul against a plain or int8-quantized weight leaf."""
    from tpuserver.ops import quant

    return quant.matmul(x, w)


def _embed_rows(params, tokens, cfg=None):
    from tpuserver.ops import quant

    return quant.gather_rows(
        params["embed"], tokens,
        dtype=cfg.dtype if cfg is not None else None,
    )


def _rms_norm(x, w, eps):
    xf = x.astype(jnp.float32)
    scale = lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * scale).astype(x.dtype) * w


def _rope(x, positions, theta):
    """Rotary embedding. x: [B, T, H, D]; positions: [T] or [B, T]."""
    d = x.shape[-1]
    freqs = 1.0 / (
        theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    )
    if positions.ndim == 1:
        positions = positions[None, :]
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,T,D/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)
    return out.astype(x.dtype)


def _expand_kv(k, n_rep):
    """GQA: repeat kv heads to full head count. [B,T,Hkv,D] -> [B,T,H,D]."""
    if n_rep == 1:
        return k
    return jnp.repeat(k, n_rep, axis=2)


def _block(params, x, positions, cfg, attn_fn, n_heads=None, n_kv_heads=None,
           reduce=None):
    """One transformer block: x [B, T, Dm] -> [B, T, Dm].

    The single source of the block math — dense forward, the tp-sharded
    SPMD forward, bulk prefill and token decode all call this with different
    ``attn_fn`` closures.  ``n_heads``/``n_kv_heads`` are the *local* head
    counts (tp-sharded callers pass per-shard values); ``reduce`` is applied
    to row-parallel matmul outputs (psum over tp in SPMD, identity here).
    """
    B, T, _ = x.shape
    hd = cfg.head_dim
    nh = n_heads if n_heads is not None else cfg.n_heads
    nkv = n_kv_heads if n_kv_heads is not None else cfg.n_kv_heads
    red = reduce if reduce is not None else (lambda y: y)
    h = _rms_norm(x, params["attn_norm"], cfg.norm_eps)
    q = _mm(h, params["wq"]).reshape(B, T, nh, hd)
    k = _mm(h, params["wk"]).reshape(B, T, nkv, hd)
    v = _mm(h, params["wv"]).reshape(B, T, nkv, hd)
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    attn = attn_fn(q, k, v)
    x = x + red(_mm(attn.reshape(B, T, nh * hd), params["wo"]))
    h = _rms_norm(x, params["mlp_norm"], cfg.norm_eps)
    gated = jax.nn.silu(_mm(h, params["w_gate"])) * _mm(h, params["w_up"])
    return x + red(_mm(gated, params["w_down"]))


def forward(params, tokens, cfg):
    """Teacher-forcing logits [B, T, vocab] (float32), single-shard attention
    (for sharded execution use ``sharded_forward``)."""
    B, T = tokens.shape
    n_rep = cfg.n_heads // cfg.n_kv_heads
    positions = jnp.arange(T)

    def attn_fn(q, k, v):
        bq, bk = _flash_blocks(T, cfg)
        if cfg.attn_impl == "pallas" and bq is not None and bk is not None:
            # MXU-tileable lengths only: the TPU lowering needs
            # (8, 128)-aligned blocks; other lengths fall through to
            # the dense path below
            from tpuserver.ops import flash_attention

            return flash_attention(
                q, _expand_kv(k, n_rep), _expand_kv(v, n_rep),
                causal=True, block_q=bq, block_k=bk,
            )
        return ring_attention(
            q, _expand_kv(k, n_rep), _expand_kv(v, n_rep), causal=True
        )

    x = _embed_rows(params, tokens, cfg)
    for layer in params["layers"]:
        x = _block(layer, x, positions, cfg, attn_fn)
    x = _rms_norm(x, params["norm"], cfg.norm_eps)
    return _mm(x, params["lm_head"]).astype(jnp.float32)


def sharded_forward(mesh, cfg):
    """shard_map-wrapped forward: batch on dp, time on sp, weights on tp."""
    from jax import shard_map

    specs = param_specs(cfg)
    fn = shard_map(
        functools.partial(_forward_spmd, cfg=cfg),
        mesh=mesh,
        in_specs=(specs, P("dp", "sp")),
        out_specs=P("dp", "sp", "tp"),
        check_vma=False,
    )
    return fn


def _forward_spmd(params, tokens, cfg):
    # Inside shard_map each device holds a [B/dp, T/sp] token block and
    # tp-sharded weights; tp matmul partial-sums are reduced explicitly.
    B, T = tokens.shape
    tp = lax.psum(1, "tp")
    if cfg.n_kv_heads % tp != 0 or cfg.n_heads % tp != 0:
        raise ValueError(
            "tp={} must divide n_heads={} and n_kv_heads={} (KV-head "
            "replication across tp is not supported)".format(
                tp, cfg.n_heads, cfg.n_kv_heads
            )
        )
    nh_loc = cfg.n_heads // tp
    nkv_loc = cfg.n_kv_heads // tp
    n_rep = nh_loc // nkv_loc
    t0 = lax.axis_index("sp") * T
    positions = t0 + jnp.arange(T)

    if cfg.sp_strategy not in ("ring", "ulysses"):
        raise ValueError(
            "unknown sp_strategy '{}' (expected 'ring' or "
            "'ulysses')".format(cfg.sp_strategy)
        )

    def attn_fn(q, k, v):
        if cfg.sp_strategy == "ulysses":
            # unexpanded kv heads ride the all_to_alls; GQA replication
            # happens after redistribution
            return ulysses_attention(
                q, k, v, axis_name="sp", causal=True, kv_repeat=n_rep,
            )
        return ring_attention(
            q, _expand_kv(k, n_rep), _expand_kv(v, n_rep),
            axis_name="sp", causal=True,
        )

    def psum_tp(y):
        return lax.psum(y, "tp")

    # embed is vocab-sharded on tp: gather local rows then psum.
    vloc = params["embed"].shape[0]
    voff = lax.axis_index("tp") * vloc
    local = tokens - voff
    hit = (local >= 0) & (local < vloc)
    x = jnp.where(
        hit[..., None],
        params["embed"][jnp.clip(local, 0, vloc - 1)],
        jnp.zeros((), params["embed"].dtype),
    )
    x = lax.psum(x, "tp")
    for layer in params["layers"]:
        x = _block(
            layer, x, positions, cfg, attn_fn,
            n_heads=nh_loc, n_kv_heads=nkv_loc, reduce=psum_tp,
        )
    x = _rms_norm(x, params["norm"], cfg.norm_eps)
    return (x @ params["lm_head"]).astype(jnp.float32)


# -- training (for the multi-chip dry-run and completeness) ------------------


def make_train_step(mesh, cfg, learning_rate=3e-4):
    """jit-compiled SPMD train step over (dp, sp, tp).

    Loss is next-token cross-entropy; gradients/optimizer state inherit the
    parameter sharding, batch is (dp, sp)-sharded; XLA inserts the psums.
    Returns (step_fn, init_fn).
    """
    import optax

    tx = optax.adamw(learning_rate)
    pspecs = param_specs(cfg)
    param_sh = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), pspecs
    )
    batch_sh = NamedSharding(mesh, P("dp", "sp"))
    fwd = sharded_forward(mesh, cfg)

    def loss_fn(params, tokens, targets):
        logits = fwd(params, tokens)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
        return jnp.mean(nll)

    def init_fn(key, tokens):
        params = init_params(key, cfg)
        params = jax.device_put(params, param_sh)
        opt_state = tx.init(params)
        return params, opt_state

    @functools.partial(
        jax.jit,
        in_shardings=(param_sh, None, batch_sh, batch_sh),
        donate_argnums=(0,),
    )
    def step_fn(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step_fn, init_fn


# -- decode (serving) --------------------------------------------------------


def init_kv_cache(cfg, batch, max_seq, dtype=None):
    """[n_layers, 2, B, max_seq, n_kv_heads, head_dim] cache."""
    dtype = dtype or cfg.dtype
    return jnp.zeros(
        (cfg.n_layers, 2, batch, max_seq, cfg.n_kv_heads, cfg.head_dim),
        dtype,
    )


def decode_crossover_length(max_seq):
    """Valid-prefix length below which the Pallas decode-attention kernel
    beats dense XLA attention against a cache padded to ``max_seq``.

    Cost model fitted to the measured table in docs/benchmarking.md
    (v5e, llama3-class head geometry): dense reads the whole padded
    cache every token — ~16.5 ns/key at S=2k degrading to ~62 ns/key at
    S=32k as its MBU collapses — while the kernel's length-clamped index
    map costs ~4.6 µs fixed + ~24.7 µs per 1024 *valid* keys.  Returns
    <= 0 when dense always wins, >= max_seq when Pallas always wins.
    """
    pts = ((2048, 16.5), (8192, 18.7), (32768, 61.8))
    if max_seq <= pts[0][0]:
        ns_per_key = pts[0][1]
    elif max_seq >= pts[-1][0]:
        ns_per_key = pts[-1][1]
    else:
        ns_per_key = pts[0][1]
        for (s0, n0), (s1, n1) in zip(pts, pts[1:]):
            if s0 <= max_seq <= s1:
                ns_per_key = n0 + (n1 - n0) * (max_seq - s0) / (s1 - s0)
                break
    dense_us = max_seq * ns_per_key / 1000.0
    return int((dense_us - 4.6) / (24.7 / 1024.0))


def _select_decode_impl(max_seq, lengths):
    """Trace-time selection for ``decode_impl="auto"``.

    Static only: a per-step ``lax.cond`` on the live length was measured
    on v5e and rejected — XLA cannot donate/alias the KV cache through
    cond branches, so every step paid cache copies and long-context
    decode collapsed ~3x (70.8 -> 23.1 tokens/sec at ctx 2176).  With a
    static ``lengths`` the crossover applies exactly; otherwise the
    kernel is chosen when it wins for the MAJORITY of possible cache
    lengths (a serving request sweeps lengths upward, so the majority
    rule tracks the time-averaged cost)."""
    cross = decode_crossover_length(max_seq)
    if cross <= 0:
        return "xla"
    if cross >= max_seq:
        return "pallas"
    if isinstance(lengths, (int, np.integer)):
        return "pallas" if int(lengths) < cross else "xla"
    return "pallas" if cross >= max_seq // 2 else "xla"


def _run_cached(params, cache, x, positions, write_pos, lengths, cfg):
    """Shared decode/prefill body: run all blocks, writing new K/V into the
    cache at ``write_pos`` and attending over cache[:lengths].

    x: [B, T, Dm] embedded inputs. Returns (x_out, new_cache)."""
    n_rep = cfg.n_heads // cfg.n_kv_heads
    new_cache = cache

    for i, layer in enumerate(params["layers"]):
        def attn_fn(q, k, v, i=i):
            nonlocal new_cache
            new_cache = new_cache.at[i, 0].set(
                lax.dynamic_update_slice_in_dim(
                    new_cache[i, 0], k.astype(new_cache.dtype), write_pos,
                    axis=1,
                )
            )
            new_cache = new_cache.at[i, 1].set(
                lax.dynamic_update_slice_in_dim(
                    new_cache[i, 1], v.astype(new_cache.dtype), write_pos,
                    axis=1,
                )
            )
            max_seq = cache.shape[3]
            pallas_block = next(
                (b for b in (256, 128) if max_seq % b == 0), None
            )
            impl = cfg.decode_impl
            if impl == "auto" and q.shape[1] == 1:
                impl = _select_decode_impl(max_seq, lengths)
            if (
                impl == "pallas"
                and q.shape[1] == 1
                and pallas_block is not None
            ):
                # the serving hot op: hand-tiled single-query decode
                # attention (GQA expansion stays in VMEM, dead cache
                # tail blocks never stream from HBM).  Equivalent mask:
                # with q_pos == lengths-1, "k > q_pos" == "k >= lengths".
                # max_seq without a tileable block falls through to the
                # dense path (like the prefill gate above) instead of
                # erroring at trace time.
                from tpuserver.ops import decode_attention

                out = decode_attention(
                    q[:, 0],
                    new_cache[i, 0],
                    new_cache[i, 1],
                    jnp.full((q.shape[0],), lengths, jnp.int32),
                    block_k=pallas_block,
                )
                return out[:, None]
            pf_bq, pf_bk = _flash_blocks(q.shape[1], cfg)
            if (
                cfg.attn_impl == "pallas"
                and q.shape[1] > 1
                and pf_bq is not None
                and pf_bk is not None
                and isinstance(write_pos, int)
                and write_pos == 0
            ):
                # prefill from position 0: the cached attention is
                # exactly causal self-attention over the prompt, so the
                # flash kernel applies (K/V still land in the cache via
                # the updates above).  Only MXU-tileable lengths — the
                # TPU lowering needs (8, 128)-aligned blocks, so odd
                # prompt lengths take the dense path.
                from tpuserver.ops import flash_attention

                return flash_attention(
                    q, _expand_kv(k, n_rep), _expand_kv(v, n_rep),
                    causal=True, block_q=pf_bq, block_k=pf_bk,
                )
            return _attend_cached(
                q, new_cache[i, 0], new_cache[i, 1], positions, lengths,
                n_rep,
            )

        x = _block(layer, x, positions, cfg, attn_fn)
    return x, new_cache


def _attend_cached(q, cache_k, cache_v, q_pos, length, n_rep):
    """q: [B, Tq, H, D] against cache [B, S, Hkv, D].

    Masks cache positions >= ``length`` (a scalar, or a per-row [B]
    vector when the continuous-batching step decodes rows at different
    sequence positions) and (causally) > the query's own global position
    ``q_pos`` [B, Tq]."""
    k = _expand_kv(cache_k, n_rep)
    v = _expand_kv(cache_v, n_rep)
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k,
        preferred_element_type=jnp.float32,
    ) / np.sqrt(q.shape[-1])
    k_idx = jnp.arange(k.shape[1])[None, None, None, :]
    if getattr(length, "ndim", 0):
        length = length.reshape(-1, 1, 1, 1)  # per-row valid prefixes
    mask = (k_idx >= length) | (k_idx > q_pos[:, None, :, None])
    s = jnp.where(mask, -jnp.inf, s)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def decode_step(params, cache, tokens, pos, cfg):
    """One token of autoregressive decode.

    tokens: [B] int32; pos: scalar int32 (current position, same for batch).
    Returns (logits [B, vocab] fp32, updated cache).
    """
    B = tokens.shape[0]
    positions = jnp.full((B, 1), pos)
    x = _embed_rows(params, tokens, cfg)[:, None, :]  # [B, 1, Dm]
    x, new_cache = _run_cached(
        params, cache, x, positions, pos, pos + 1, cfg
    )
    x = _rms_norm(x, params["norm"], cfg.norm_eps)
    logits = _mm(x[:, 0, :], params["lm_head"]).astype(jnp.float32)
    return logits, new_cache


def prefill(params, cache, tokens, cfg):
    """Bulk-run the prompt through the cache; returns (last logits, cache).

    tokens: [B, T].  One batched pass — the [T, T] attention stays
    MXU-shaped and K/V blocks land in the cache with a single
    dynamic_update_slice per layer (not T sequential steps)."""
    B, T = tokens.shape
    positions = jnp.tile(jnp.arange(T)[None, :], (B, 1))
    x = _embed_rows(params, tokens, cfg)
    x, new_cache = _run_cached(params, cache, x, positions, 0, T, cfg)
    x = _rms_norm(x, params["norm"], cfg.norm_eps)
    logits = _mm(x[:, -1, :], params["lm_head"]).astype(jnp.float32)
    return logits, new_cache


def decode_chunk(params, cache, logits, pos, cfg, chunk):
    """Greedy-decode ``chunk`` tokens in ONE device dispatch.

    Steady-state decode is dispatch-latency-bound when the host is far
    from the chip (each per-token round trip costs a full host<->device
    hop); scanning a fixed chunk of argmax+decode_step pairs inside one
    jitted call amortizes that hop over ``chunk`` tokens.  Greedy
    sampling keeps the result bit-identical to per-token decode.

    logits: [B, vocab] for the NEXT position (from prefill or the prior
    chunk).  Returns (tokens [chunk, B], logprobs [chunk, B],
    next_logits, cache); positions pos..pos+chunk-1 are written.
    """

    def body(carry, _):
        logits, cache, pos = carry
        logp = jax.nn.log_softmax(logits, axis=-1)
        token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        tok_logp = jnp.take_along_axis(
            logp, token[:, None], axis=-1)[:, 0]
        next_logits, cache = decode_step(params, cache, token, pos, cfg)
        return (next_logits, cache, pos + 1), (token, tok_logp)

    (next_logits, cache, _), (tokens, logps) = lax.scan(
        body, (logits, cache, pos), None, length=chunk
    )
    return tokens, logps, next_logits, cache


# -- continuous batching (the slotted decode step) ---------------------------


def prefill_bucket(cfg, max_seq, true_len):
    """The padded length the scheduler should prefill a ``true_len``
    prompt at: the next power of two (min 8, capped at ``max_seq``) —
    UNLESS padding would change which prefill attention path runs.

    With ``attn_impl="pallas"`` the flash kernel engages only at
    tileable lengths; padding a dense-length prompt to a tileable bucket
    (or changing the tile pair) would alter the accumulation order of
    the admission prefill vs the single-stream path's exact-length
    prefill, and a near-tie in the first token's logits could flip the
    greedy argmax — breaking the token-identity contract.  Such lengths
    compile exactly instead (the pre-bucketing behavior); everything on
    the dense path buckets freely."""
    bucket = 8
    while bucket < true_len:
        bucket <<= 1
    bucket = min(bucket, max_seq)
    if bucket == true_len or cfg.attn_impl != "pallas":
        return bucket

    def dense(T):
        return None in _flash_blocks(T, cfg)

    return bucket if dense(true_len) and dense(bucket) else true_len


def prefill_to_length(params, cache, tokens, true_len, cfg):
    """Prefill a PADDED prompt, returning the logits at ``true_len - 1``.

    The admission prefill compiles one executable per distinct prompt
    length; under continuous batching every novel length would stall
    ALL in-flight streams for a full model compile.  Padding prompts to
    a few fixed buckets bounds the compile set — and causal attention
    makes the result exact: position ``true_len - 1`` attends only
    positions <= itself, so the padding rows (garbage K/V written at
    ``true_len..T-1``, later masked by the slot's length and overwritten
    by decode steps) cannot influence the returned logits.
    """
    B, T = tokens.shape
    positions = jnp.tile(jnp.arange(T)[None, :], (B, 1))
    x = _embed_rows(params, tokens, cfg)
    x, new_cache = _run_cached(params, cache, x, positions, 0, T, cfg)
    x = _rms_norm(x, params["norm"], cfg.norm_eps)
    last = lax.dynamic_slice_in_dim(x, true_len - 1, 1, axis=1)[:, 0]
    logits = _mm(last, params["lm_head"]).astype(jnp.float32)
    return logits, new_cache


def batched_decode_step(params, cache, tokens, positions, cfg):
    """One decode token per cache SLOT at per-slot positions — the
    compute heart of the continuous-batching scheduler
    (``tpuserver.scheduler``).

    Where ``decode_step`` advances one sequence at a shared scalar
    ``pos``, here every cache row is an independent in-flight generation:
    ``tokens`` [S] int32 are the rows' next input tokens and ``positions``
    [S] int32 their current write positions.  Each row's K/V lands at its
    own position (a scatter instead of a dynamic_update_slice) and
    attention masks each row to its own valid prefix
    (``positions + 1``).  Rows holding no live request use the sentinel
    position ``max_seq`` — out of bounds, so their cache writes DROP
    (mode="drop") and a finished-but-still-in-flight slot's parked rows
    are never corrupted.

    Returns (logits [S, vocab] fp32, new cache).  Per-row math is
    identical to ``decode_step``'s, which is what makes greedy tokens
    from N interleaved slots equal to N sequential single-stream runs.
    """
    S = tokens.shape[0]
    max_seq = cache.shape[3]
    q_pos = positions[:, None]  # [S, 1]
    # inert rows (sentinel position max_seq) clamp to length 1, not
    # max_seq: the decode-attention kernel skips blocks past each row's
    # valid prefix, and an empty slot must not stream its whole dead
    # cache from HBM every step (length 0 would NaN the softmax; the
    # one garbage position attended is discarded with the row's output)
    lengths = jnp.where(positions >= max_seq, 1, positions + 1)
    n_rep = cfg.n_heads // cfg.n_kv_heads
    rows = jnp.arange(S)
    x = _embed_rows(params, tokens, cfg)[:, None, :]  # [S, 1, Dm]
    new_cache = cache
    pallas_block = next((b for b in (256, 128) if max_seq % b == 0), None)
    impl = cfg.decode_impl
    if impl == "auto":
        impl = _select_decode_impl(max_seq, None)

    for i, layer in enumerate(params["layers"]):
        def attn_fn(q, k, v, i=i):
            nonlocal new_cache
            new_cache = new_cache.at[i, 0, rows, positions].set(
                k[:, 0].astype(new_cache.dtype), mode="drop"
            )
            new_cache = new_cache.at[i, 1, rows, positions].set(
                v[:, 0].astype(new_cache.dtype), mode="drop"
            )
            if impl == "pallas" and pallas_block is not None:
                # the decode-attention kernel already takes per-row
                # lengths — continuous batching is its natural shape
                from tpuserver.ops import decode_attention

                out = decode_attention(
                    q[:, 0],
                    new_cache[i, 0],
                    new_cache[i, 1],
                    lengths.astype(jnp.int32),
                    block_k=pallas_block,
                )
                return out[:, None]
            return _attend_cached(
                q, new_cache[i, 0], new_cache[i, 1], q_pos, lengths, n_rep
            )

        x = _block(layer, x, q_pos, cfg, attn_fn)
    x = _rms_norm(x, params["norm"], cfg.norm_eps)
    logits = _mm(x[:, 0, :], params["lm_head"]).astype(jnp.float32)
    return logits, new_cache


def scheduler_step(params, cache, logits_all, positions, active,
                   forced, forced_mask, cfg):
    """One continuous-batching iteration over every cache slot, in ONE
    device dispatch.

    Each slot's next token is sampled greedily from its ``logits_all``
    row — except slots replaying a resumed prompt, whose ``forced``
    token is taken instead (``forced_mask``); those steps only feed the
    cache, the scheduler emits nothing for them.  The batched decode
    step then writes every active row's K/V at its own position.
    Inactive rows keep their previous logits so a dead slot's state
    stays inert until an admission overwrites it.

    Returns (tokens [S], logprobs [S], next logits [S, vocab], cache).
    """
    logp = jax.nn.log_softmax(logits_all, axis=-1)
    greedy = jnp.argmax(logits_all, axis=-1).astype(jnp.int32)
    tokens = jnp.where(forced_mask, forced, greedy)
    tok_logp = jnp.take_along_axis(logp, tokens[:, None], axis=-1)[:, 0]
    new_logits, new_cache = batched_decode_step(
        params, cache, tokens, positions, cfg
    )
    new_logits = jnp.where(active[:, None], new_logits, logits_all)
    return tokens, tok_logp, new_logits, new_cache


def scheduler_admit(cache, logits_all, slot_cache, slot_logits, slot):
    """Admit one prefilled request into the slotted arrays: write its
    [n_layers, 2, 1, S, Hkv, hd] cache into batch row ``slot`` and its
    next-token logits [1, vocab] into the matching ``logits_all`` row.
    ``slot`` is a traced scalar — one compile covers every slot."""
    cache = lax.dynamic_update_slice_in_dim(
        cache, slot_cache.astype(cache.dtype), slot, axis=2
    )
    logits_all = lax.dynamic_update_slice_in_dim(
        logits_all, slot_logits.astype(logits_all.dtype), slot, axis=0
    )
    return cache, logits_all


def scheduler_extract(cache, slot):
    """One slot's cache rows as a fresh [n_layers, 2, 1, S, Hkv, hd]
    array — the same shape the single-stream path parks in an XLA shm
    region, so park/resume interoperates across both modes."""
    return lax.dynamic_slice_in_dim(cache, slot, 1, axis=2)


# -- paged KV (block-granular cache pool) ------------------------------------


def init_paged_kv_cache(cfg, n_pages, page_size, dtype=None):
    """[n_layers, 2, n_pages, page_size, n_kv_heads, head_dim] page
    pool — the paged form of :func:`init_kv_cache`.  A sequence's KV
    lives scattered across pages named by its page table; page id
    ``n_pages`` is the out-of-bounds scatter sentinel (writes drop)."""
    dtype = dtype or cfg.dtype
    return jnp.zeros(
        (cfg.n_layers, 2, n_pages, page_size, cfg.n_kv_heads,
         cfg.head_dim),
        dtype,
    )


def paged_batched_decode_step(params, pages, tokens, page_tables,
                              positions, cfg):
    """:func:`batched_decode_step` over a paged pool: one decode token
    per sequence row, with each row's KV scattered across the physical
    pages its ``page_tables`` row names.

    ``pages`` is the pool from :func:`init_paged_kv_cache`;
    ``page_tables`` [S, pages_per_seq] int32 maps each row's logical
    pages to physical ids (entries may be the sentinel ``n_pages`` for
    unreserved logical pages — they are never read below the row's
    valid length and never written).  Per layer the row's pages gather
    into the same contiguous [S, max_seq] view the slotted step
    attends over — identical values in identical order, so greedy
    tokens are bitwise equal to the contiguous step's (A/B-pinned in
    tests/test_paged_kv.py).  The gather is the CPU-sim functional
    model of paged attention; a production TPU path would stream pages
    inside a Pallas kernel instead of materializing the view.

    New K/V writes land at (``page_tables[s, positions[s] //
    page_size]``, ``positions[s] % page_size``); rows at the sentinel
    position ``max_seq`` drop their writes, exactly like the slotted
    step's out-of-bounds rows.
    """
    S = tokens.shape[0]
    n_pages, page = pages.shape[2], pages.shape[3]
    ppseq = page_tables.shape[1]
    max_seq = ppseq * page
    # inert rows clamp to length 1 (see batched_decode_step)
    lengths = jnp.where(positions >= max_seq, 1, positions + 1)
    logical = jnp.clip(positions // page, 0, ppseq - 1)
    phys = jnp.take_along_axis(page_tables, logical[:, None], axis=1)[:, 0]
    # sentinel rows scatter out of bounds -> dropped (mode="drop")
    phys = jnp.where(positions >= max_seq, n_pages, phys)
    offs = positions % page
    q_pos = positions[:, None]
    n_rep = cfg.n_heads // cfg.n_kv_heads
    x = _embed_rows(params, tokens, cfg)[:, None, :]  # [S, 1, Dm]
    new_pages = pages
    # unreserved logical pages clip to a valid (arbitrary) physical
    # page: everything they contribute sits beyond the row's valid
    # length and is masked
    tbl = jnp.clip(page_tables, 0, n_pages - 1)
    pallas_block = next((b for b in (256, 128) if max_seq % b == 0), None)
    impl = cfg.decode_impl
    if impl == "auto":
        impl = _select_decode_impl(max_seq, None)

    for i, layer in enumerate(params["layers"]):
        def attn_fn(q, k, v, i=i):
            nonlocal new_pages
            new_pages = new_pages.at[i, 0, phys, offs].set(
                k[:, 0].astype(new_pages.dtype), mode="drop"
            )
            new_pages = new_pages.at[i, 1, phys, offs].set(
                v[:, 0].astype(new_pages.dtype), mode="drop"
            )
            tail = new_pages.shape[4:]
            k_seq = new_pages[i, 0][tbl].reshape(S, max_seq, *tail)
            v_seq = new_pages[i, 1][tbl].reshape(S, max_seq, *tail)
            if impl == "pallas" and pallas_block is not None:
                # the gathered view is a standard contiguous cache:
                # the decode-attention kernel applies unchanged
                from tpuserver.ops import decode_attention

                out = decode_attention(
                    q[:, 0], k_seq, v_seq, lengths.astype(jnp.int32),
                    block_k=pallas_block,
                )
                return out[:, None]
            return _attend_cached(q, k_seq, v_seq, q_pos, lengths, n_rep)

        x = _block(layer, x, q_pos, cfg, attn_fn)
    x = _rms_norm(x, params["norm"], cfg.norm_eps)
    logits = _mm(x[:, 0, :], params["lm_head"]).astype(jnp.float32)
    return logits, new_pages


def paged_scheduler_step(params, pages, logits_all, page_tables,
                         positions, active, forced, forced_mask, cfg):
    """:func:`scheduler_step` on the paged pool: greedy-or-forced
    token per row, then one :func:`paged_batched_decode_step`.  Same
    sampling math as the slotted form — the page indirection changes
    where K/V bytes live, never what they are."""
    logp = jax.nn.log_softmax(logits_all, axis=-1)
    greedy = jnp.argmax(logits_all, axis=-1).astype(jnp.int32)
    tokens = jnp.where(forced_mask, forced, greedy)
    tok_logp = jnp.take_along_axis(logp, tokens[:, None], axis=-1)[:, 0]
    new_logits, new_pages = paged_batched_decode_step(
        params, pages, tokens, page_tables, positions, cfg
    )
    new_logits = jnp.where(active[:, None], new_logits, logits_all)
    return tokens, tok_logp, new_logits, new_pages


def paged_spec_step(params, pages, logits_all, page_tables, positions,
                    active, forced, forced_mask, draft, draft_len, cfg):
    """Multi-token speculative verify: :func:`paged_scheduler_step`
    followed by up to K drafted continuation tokens, all inside ONE
    dispatch (``tpuserver.speculative`` is the draft source).

    ``draft`` [S, K] int32 holds each row's proposed continuation and
    ``draft_len`` [S] int32 how many of those entries are real (0 =
    no speculation for the row — forced-replay rows and throttled
    streams).  The step is an unrolled chain of K+1 sub-steps, each
    the *exact* op sequence of :func:`paged_scheduler_step`'s math
    (log_softmax → argmax → :func:`paged_batched_decode_step`), so
    every intermediate logits row is bitwise identical to what k
    separate single-token steps would compute — the token-identity
    contract holds by construction, not by tolerance (A/B-pinned in
    tests/test_speculative.py).

    Sub-step 0 feeds the ordinary greedy-or-forced token at
    ``positions``; sub-step j >= 1 feeds ``draft[:, j-1]`` at
    ``positions + j`` (rows past their ``draft_len`` feed at the
    sentinel ``max_seq`` — writes drop, the row is inert for that
    sub-step).  Greedy acceptance is computed in-graph: row ``i``
    accepts the longest prefix of its drafts where the previous
    sub-step's argmax equals the drafted token, and its returned
    logits are the sub-step outputs at that acceptance depth —
    selected by GATHER, never by masked arithmetic, so a poisoned
    row's NaN logits reach the host quarantine path intact instead
    of corrupting the select.

    Rejected drafts leave garbage K/V at ``positions + accept + 1``
    onward; those positions sit beyond the row's advanced write
    cursor, so the next step (or the retirement donation's
    ``min(pos, known)`` bound) overwrites or ignores them — the
    rollback is a host-side cursor move, never a device copy.

    Returns ``(tokens [S, K+1], logprobs [S, K+1], accept [S],
    new_logits [S, vocab], new_pages)``: ``tokens[:, 0]`` is the
    base token, ``tokens[:, j]`` the j-th draft, and the host emits
    ``tokens[i, :1 + accept[i]]``.
    """
    S, K = draft.shape
    page = pages.shape[3]
    max_seq = page_tables.shape[1] * page
    logp = jax.nn.log_softmax(logits_all, axis=-1)
    greedy = jnp.argmax(logits_all, axis=-1).astype(jnp.int32)
    t0 = jnp.where(forced_mask, forced, greedy)
    lp0 = jnp.take_along_axis(logp, t0[:, None], axis=-1)[:, 0]
    cur, new_pages = paged_batched_decode_step(
        params, pages, t0, page_tables, positions, cfg
    )
    toks = [t0]
    lps = [lp0]
    stack = [cur]   # stack[j] = logits after feeding sub-step j
    matches = []
    for j in range(1, K + 1):
        cand = draft[:, j - 1]
        fed = j <= draft_len
        logp_j = jax.nn.log_softmax(cur, axis=-1)
        g = jnp.argmax(cur, axis=-1).astype(jnp.int32)
        matches.append((g == cand) & fed)
        lps.append(
            jnp.take_along_axis(logp_j, cand[:, None], axis=-1)[:, 0]
        )
        toks.append(cand)
        pos_j = jnp.where(fed, positions + j, max_seq)
        cur, new_pages = paged_batched_decode_step(
            params, new_pages, cand, page_tables, pos_j, cfg
        )
        stack.append(cur)
    match_stack = jnp.stack(matches, axis=0).astype(jnp.int32)  # [K, S]
    accept = jnp.sum(jnp.cumprod(match_stack, axis=0), axis=0)
    accept = accept.astype(jnp.int32)
    l_stack = jnp.stack(stack, axis=0)  # [K+1, S, vocab]
    final = l_stack[accept, jnp.arange(S)]
    final = jnp.where(active[:, None], final, logits_all)
    return (
        jnp.stack(toks, axis=1),
        jnp.stack(lps, axis=1),
        accept,
        final,
        new_pages,
    )


def paged_admit(pages, logits_all, slot_cache, slot_logits, dest_ids,
                slot):
    """Admit one prefilled request into the paged pool: the single-row
    contiguous cache [L, 2, 1, max_seq, Hkv, hd] splits into
    ``pages_per_seq`` logical pages and scatters to the physical ids
    ``dest_ids`` names (the sentinel ``n_pages`` drops a page — shared
    prefix pages already live in the pool and must not be rewritten).
    The row's next-token logits land in ``logits_all`` row ``slot``."""
    page = pages.shape[3]
    ppseq = dest_ids.shape[0]
    src = slot_cache.reshape(
        slot_cache.shape[0], 2, ppseq, page, *slot_cache.shape[4:]
    )
    pages = pages.at[:, :, dest_ids].set(
        src.astype(pages.dtype), mode="drop"
    )
    logits_all = lax.dynamic_update_slice_in_dim(
        logits_all, slot_logits.astype(logits_all.dtype), slot, axis=0
    )
    return pages, logits_all


def paged_gather(pages, page_ids):
    """One sequence's pages as a fresh single-row contiguous cache
    [L, 2, 1, max_seq, Hkv, hd] — the park/extract shape (so paged
    park/resume interoperates with the single-stream path) and the
    prefix-restore source a shared-prefix admission prefills on top
    of.  Sentinel/unreserved ids gather as zeros."""
    n_pages, page = pages.shape[2], pages.shape[3]
    ppseq = page_ids.shape[0]
    valid = (page_ids >= 0) & (page_ids < n_pages)
    ids = jnp.clip(page_ids, 0, n_pages - 1)
    rows = pages[:, :, ids]  # [L, 2, ppseq, page, Hkv, hd]
    rows = jnp.where(
        valid[None, None, :, None, None, None], rows,
        jnp.zeros((), rows.dtype),
    )
    return rows.reshape(
        pages.shape[0], 2, 1, ppseq * page, *pages.shape[4:]
    )


def prefill_span(params, cache, tokens, start, logits_at, cfg):
    """Prefill a token span at positions ``start..start+T-1`` into a
    single-row contiguous cache — the chunked-prefill and
    shared-prefix-suffix building block.

    Generalizes :func:`prefill_to_length`: K/V land at ``write_pos =
    start`` and queries attend the cache's first ``start + T``
    positions under the causal mask, so a span conditioned on an
    already-present prefix (earlier chunks, or a radix-cache restore)
    computes exactly what a from-zero prefill would.  All keys read
    from the cache post-write (the dense cached path), so chunked
    output is bitwise identical to one-shot dense prefill — the
    token-identity contract tests/test_paged_kv.py pins.  The caller
    guarantees ``start + T <= max_seq`` (XLA would silently clamp the
    write start otherwise) and that the flash prefill kernel is not in
    play for this model (``make_scheduler_fns`` gates chunking/sharing
    with ``span_safe`` exactly like :func:`prefill_bucket` gates
    padding).

    Returns the logits at chunk-relative index ``logits_at`` (only
    meaningful on the span containing the prompt's last token) and
    the updated cache."""
    B, T = tokens.shape
    positions = start + jnp.tile(jnp.arange(T)[None, :], (B, 1))
    x = _embed_rows(params, tokens, cfg)
    x, new_cache = _run_cached(
        params, cache, x, positions, start, start + T, cfg
    )
    x = _rms_norm(x, params["norm"], cfg.norm_eps)
    last = lax.dynamic_slice_in_dim(x, logits_at, 1, axis=1)[:, 0]
    logits = _mm(last, params["lm_head"]).astype(jnp.float32)
    return logits, new_cache


def make_scheduler_fns(cfg, max_seq, max_slots, mesh=None, quantized=False,
                       page_size=16, kv_pages=None):
    """Compiled function bundle for the continuous-batching scheduler,
    over a block-paged KV pool.

    The device cache is a page pool [n_layers, 2, kv_pages, page_size,
    n_kv_heads, head_dim] (:func:`init_paged_kv_cache`) rather than
    ``max_slots`` contiguous rows: a sequence occupies only the pages
    its length spans, page tables map logical to physical pages, and
    the scheduler's host-side allocator/radix tree
    (``tpuserver.paging``) decides who owns what.  ``kv_pages``
    defaults to ``max_slots * max_seq / page_size`` — byte-identical
    capacity to the old slotted cache, which shared prefixes and short
    spans then stretch across MORE concurrent streams.

    Returns a dict of:

    - ``init_cache()`` — the page pool
    - ``init_slot_cache()`` — a single-row contiguous cache for
      prefill-on-admit (scattered into pages by ``admit``)
    - ``init_logits()`` — [max_slots, vocab] fp32 zeros
    - ``prefill(params, slot_cache, tokens, true_len)`` — the one-shot
      admission prefill (:func:`prefill_to_length`)
    - ``prefill_span(params, slot_cache, tokens, start, logits_at)`` —
      the chunked / shared-prefix-suffix prefill
      (:func:`prefill_span`)
    - ``prefill_bucket(true_len)`` — the padded length to use
    - ``step(params, pages, logits, page_tables, positions, active,
      forced, forced_mask)`` — :func:`paged_scheduler_step`, pages and
      logits donated
    - ``spec_step(params, pages, logits, page_tables, positions,
      active, forced, forced_mask, draft, draft_len)`` —
      :func:`paged_spec_step`, the multi-token speculative verify
      (pages and logits donated; one compile per distinct K)
    - ``admit(pages, logits, slot_cache, slot_logits, dest_ids,
      slot)`` — :func:`paged_admit`, pages and logits donated
    - ``gather(pages, page_ids)`` — :func:`paged_gather`: the park
      copy AND the shared-prefix restore (pages NOT donated)
    - ``page_size`` / ``pages_per_seq`` / ``n_pages`` — the pool
      geometry the scheduler's allocator mirrors
    - ``span_safe`` — whether chunked/shared prefill preserves the
      one-shot kernel choice (False for flash-prefill configs: a
      dense chunk vs a one-shot flash pass could flip a near-tie
      greedy argmax, the same hazard :func:`prefill_bucket` guards,
      so the scheduler falls back to whole-prompt prefill there)

    With a ``mesh`` the bundle is the GSPMD form: params
    Megatron-split, the page pool and slot cache kv-head-sharded over
    tp (``cache_spec`` — the page axes are unsharded, so the
    gather/scatter indexing stays collective-free), control vectors
    replicated.
    """
    if mesh is not None and (cfg.n_heads % mesh.shape["tp"]
                             or cfg.n_kv_heads % mesh.shape["tp"]):
        raise ValueError(
            "tp={} must divide n_heads={} and n_kv_heads={}".format(
                mesh.shape["tp"], cfg.n_heads, cfg.n_kv_heads
            )
        )
    page_size = int(page_size)
    if page_size < 1 or max_seq % page_size:
        raise ValueError(
            "page_size must be >= 1 and divide max_seq (got page_size="
            "{}, max_seq={}): the park/extract row shape must stay "
            "[.., max_seq, ..] for single-stream interop".format(
                page_size, max_seq
            )
        )
    pages_per_seq = max_seq // page_size
    n_pages = int(kv_pages) if kv_pages is not None \
        else max_slots * pages_per_seq
    if n_pages < pages_per_seq:
        raise ValueError(
            "kv_pages={} cannot hold even one full-length sequence "
            "({} pages of {} tokens)".format(
                n_pages, pages_per_seq, page_size
            )
        )
    if mesh is None:
        step = jax.jit(
            functools.partial(paged_scheduler_step, cfg=cfg),
            donate_argnums=(1, 2),
        )
        spec_step = jax.jit(
            functools.partial(paged_spec_step, cfg=cfg),
            donate_argnums=(1, 2),
        )
        admit = jax.jit(paged_admit, donate_argnums=(0, 1))
        gather = jax.jit(paged_gather)
        prefill_fn = jax.jit(functools.partial(prefill_to_length, cfg=cfg))
        prefill_span_fn = jax.jit(
            functools.partial(prefill_span, cfg=cfg),
        )

        def init_cache():
            return init_paged_kv_cache(cfg, n_pages, page_size)

        def init_slot_cache():
            return init_kv_cache(cfg, 1, max_seq)

        def init_logits():
            return jnp.zeros((max_slots, cfg.vocab), jnp.float32)

    else:
        param_sh, cache_sh, repl = serving_shardings(
            mesh, cfg, quantized=quantized
        )
        step = jax.jit(
            functools.partial(paged_scheduler_step, cfg=cfg),
            in_shardings=(param_sh, cache_sh, repl, repl, repl, repl,
                          repl, repl),
            out_shardings=(repl, repl, repl, cache_sh),
            donate_argnums=(1, 2),
        )
        spec_step = jax.jit(
            functools.partial(paged_spec_step, cfg=cfg),
            in_shardings=(param_sh, cache_sh, repl, repl, repl, repl,
                          repl, repl, repl, repl),
            out_shardings=(repl, repl, repl, repl, cache_sh),
            donate_argnums=(1, 2),
        )
        admit = jax.jit(
            paged_admit,
            in_shardings=(cache_sh, repl, cache_sh, repl, repl, repl),
            out_shardings=(cache_sh, repl),
            donate_argnums=(0, 1),
        )
        gather = jax.jit(
            paged_gather,
            in_shardings=(cache_sh, repl),
            out_shardings=cache_sh,
        )
        prefill_fn = jax.jit(
            functools.partial(prefill_to_length, cfg=cfg),
            in_shardings=(param_sh, cache_sh, repl, repl),
            out_shardings=(repl, cache_sh),
        )
        prefill_span_fn = jax.jit(
            functools.partial(prefill_span, cfg=cfg),
            in_shardings=(param_sh, cache_sh, repl, repl, repl),
            out_shardings=(repl, cache_sh),
        )

        def init_cache():
            return jax.device_put(
                init_paged_kv_cache(cfg, n_pages, page_size), cache_sh
            )

        def init_slot_cache():
            return jax.device_put(init_kv_cache(cfg, 1, max_seq), cache_sh)

        def init_logits():
            return jax.device_put(
                jnp.zeros((max_slots, cfg.vocab), jnp.float32), repl
            )

    return {
        "init_cache": init_cache,
        "init_slot_cache": init_slot_cache,
        "init_logits": init_logits,
        "prefill": prefill_fn,
        "prefill_span": prefill_span_fn,
        "prefill_bucket": functools.partial(prefill_bucket, cfg, max_seq),
        "step": step,
        "spec_step": spec_step,
        "admit": admit,
        "gather": gather,
        "page_size": page_size,
        "pages_per_seq": pages_per_seq,
        "n_pages": n_pages,
        "span_safe": cfg.attn_impl != "pallas",
    }


# -- tensor-parallel serving (decode over a tp mesh) -------------------------


def cache_spec(cfg):
    """PartitionSpec of the KV cache [n_layers, 2, B, S, n_kv_heads, hd]:
    kv heads sharded over tp — each tp shard owns its heads' cache rows,
    so cache reads/writes during decode are collective-free."""
    return P(None, None, None, None, "tp", None)


def make_tp_serving(mesh, cfg, chunk=8, donate=True, quantized=False):
    """Tensor-parallel prefill + chunked decode over a mesh's ``tp`` axis.

    Where training uses an explicit ``shard_map`` (psums spelled out),
    serving uses the pure GSPMD form: jit with ``NamedSharding``
    annotations on params (Megatron column/row split, ``param_specs``)
    and cache (kv heads on tp, ``cache_spec``) and let XLA place the
    collectives — one all-reduce after each row-parallel matmul, the
    attention itself collective-free because each shard holds exactly
    its own heads' Q and KV rows.  The TPU-native analogue of the
    reference stack's multi-GPU serving (its clients drive
    NCCL-backed backends; here the backend itself is the sharded jit).

    Requires tp | n_heads and tp | n_kv_heads.  Returns
    ``(init_cache, prefill_fn, decode_fn)``; ``decode_fn`` is
    ``decode_chunk`` with the cache donated (pass ``donate=False`` when
    the caller needs the input cache afterwards, e.g. A/B tests).
    """
    tp = mesh.shape["tp"]
    if cfg.n_heads % tp or cfg.n_kv_heads % tp:
        raise ValueError(
            "tp={} must divide n_heads={} and n_kv_heads={}".format(
                tp, cfg.n_heads, cfg.n_kv_heads
            )
        )
    param_sh, cache_sh, repl = serving_shardings(
        mesh, cfg, quantized=quantized
    )

    prefill_fn = jax.jit(
        functools.partial(prefill, cfg=cfg),
        in_shardings=(param_sh, cache_sh, repl),
        out_shardings=(repl, cache_sh),
    )
    decode_fn = jax.jit(
        functools.partial(decode_chunk, cfg=cfg, chunk=chunk),
        in_shardings=(param_sh, cache_sh, repl, repl),
        out_shardings=(repl, repl, repl, cache_sh),
        donate_argnums=(1,) if donate else (),
    )

    def init_cache(batch, max_seq):
        return jax.device_put(
            init_kv_cache(cfg, batch, max_seq), cache_sh
        )

    return init_cache, prefill_fn, decode_fn


def serving_shardings(mesh, cfg, quantized=False, quantized_embed=False):
    """(param_sh, cache_sh, repl) NamedSharding trees for TP serving —
    the single source shared by ``make_tp_serving``, ``make_tp_step``
    and the serving model's ``device_put`` of loaded params."""
    param_sh = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        param_specs(
            cfg, quantized=quantized, quantized_embed=quantized_embed
        ),
    )
    cache_sh = NamedSharding(mesh, cache_spec(cfg))
    repl = NamedSharding(mesh, P())
    return param_sh, cache_sh, repl


def make_tp_step(mesh, cfg, donate=True, quantized=False):
    """Single-token tensor-parallel ``decode_step`` (same sharding rules
    as ``make_tp_serving``) — the per-token path serving uses for chunk
    tails and for feeding resumed-prompt tokens into a parked cache."""
    param_sh, cache_sh, repl = serving_shardings(
        mesh, cfg, quantized=quantized
    )
    return jax.jit(
        functools.partial(decode_step, cfg=cfg),
        in_shardings=(param_sh, cache_sh, repl, repl),
        out_shardings=(repl, cache_sh),
        donate_argnums=(1,) if donate else (),
    )
