"""Small fixture models: the `simple` add/sub model from the Triton
quick-start (2xINT32[16] -> sum/diff; reference docs/quick_start.md:75-108),
identity models, and a stateful sequence model."""

import numpy as np

from tpuserver.core import Model, TensorSpec


class SimpleModel(Model):
    """INPUT0+INPUT1 -> OUTPUT0, INPUT0-INPUT1 -> OUTPUT1 (INT32[1,16]).

    Plain numpy, not a JaxModel: the op is ~2us and the request round trip
    ~300us, so per-request jax dispatch/device_put would multiply the
    serving cost of this latency-benchmark fixture several-fold (the
    analogue of the reference's instance_group KIND_CPU placement for the
    quick-start `simple` model)."""

    name = "simple"
    platform = "python"
    backend = "python"
    max_batch_size = 8
    inputs = (
        TensorSpec("INPUT0", "INT32", [16]),
        TensorSpec("INPUT1", "INT32", [16]),
    )
    outputs = (
        TensorSpec("OUTPUT0", "INT32", [16]),
        TensorSpec("OUTPUT1", "INT32", [16]),
    )

    def execute(self, inputs, request):
        in0 = np.asarray(inputs["INPUT0"])
        in1 = np.asarray(inputs["INPUT1"])
        return {"OUTPUT0": in0 + in1, "OUTPUT1": in0 - in1}


class SimpleStringModel(Model):
    """BYTES add/sub model: string-encoded int32s in, string sums out
    (mirror of the reference's simple_string fixture)."""

    name = "simple_string"
    platform = "python"
    backend = "python"
    max_batch_size = 8
    inputs = (
        TensorSpec("INPUT0", "BYTES", [16]),
        TensorSpec("INPUT1", "BYTES", [16]),
    )
    outputs = (
        TensorSpec("OUTPUT0", "BYTES", [16]),
        TensorSpec("OUTPUT1", "BYTES", [16]),
    )

    def execute(self, inputs, request):
        in0 = np.array(
            [int(v) for v in inputs["INPUT0"].reshape(-1)], dtype=np.int64
        ).reshape(inputs["INPUT0"].shape)
        in1 = np.array(
            [int(v) for v in inputs["INPUT1"].reshape(-1)], dtype=np.int64
        ).reshape(inputs["INPUT1"].shape)
        add = in0 + in1
        sub = in0 - in1
        return {
            "OUTPUT0": np.array(
                [str(v).encode() for v in add.reshape(-1)], dtype=np.object_
            ).reshape(add.shape),
            "OUTPUT1": np.array(
                [str(v).encode() for v in sub.reshape(-1)], dtype=np.object_
            ).reshape(sub.shape),
        }


class IdentityFP32Model(Model):
    # passthrough: numpy, for the same latency reason as SimpleModel
    name = "identity_fp32"
    platform = "python"
    backend = "python"
    max_batch_size = 0
    inputs = (TensorSpec("INPUT0", "FP32", [-1, -1]),)
    outputs = (TensorSpec("OUTPUT0", "FP32", [-1, -1]),)

    def execute(self, inputs, request):
        return {"OUTPUT0": inputs["INPUT0"]}


class IdentityBF16Model(Model):
    """BF16 passthrough — exercises the TPU-native bf16 wire path."""

    name = "identity_bf16"
    platform = "python"
    backend = "python"
    max_batch_size = 0
    inputs = (TensorSpec("INPUT0", "BF16", [-1, -1]),)
    outputs = (TensorSpec("OUTPUT0", "BF16", [-1, -1]),)

    def execute(self, inputs, request):
        return {"OUTPUT0": inputs["INPUT0"]}


class IdentityStringModel(Model):
    name = "identity_string"
    platform = "python"
    backend = "python"
    max_batch_size = 0
    inputs = (TensorSpec("INPUT0", "BYTES", [-1]),)
    outputs = (TensorSpec("OUTPUT0", "BYTES", [-1]),)

    def execute(self, inputs, request):
        return {"OUTPUT0": inputs["INPUT0"]}


class SequenceAccumulateModel(Model):
    """Stateful sequence model: running int32 sum per sequence id.

    Exercises the sequence_id/sequence_start/sequence_end request controls
    (reference common.h:177-194) end-to-end.
    """

    name = "sequence_accumulate"
    platform = "python"
    backend = "python"
    max_batch_size = 0
    sequence = True
    inputs = (TensorSpec("INPUT", "INT32", [1]),)
    outputs = (TensorSpec("OUTPUT", "INT32", [1]),)

    def execute_sequence(self, inputs, state, request):
        acc = state if state is not None else np.zeros([1], dtype=np.int32)
        acc = acc + inputs["INPUT"].astype(np.int32)
        return {"OUTPUT": acc}, acc


class DelayedIdentityModel(Model):
    """INT32 passthrough that sleeps DELAY_US[0] microseconds (or the
    ``delay_us`` request parameter) before responding — fixture for
    client-timeout / cancellation paths (role of the reference's delayed
    custom_identity_int32 used by client_timeout_test.cc)."""

    name = "delayed_identity"
    platform = "python"
    backend = "python"
    max_batch_size = 0
    inputs = (
        TensorSpec("INPUT0", "INT32", [-1]),
        TensorSpec("DELAY_US", "UINT32", [1]),
    )
    outputs = (TensorSpec("OUTPUT0", "INT32", [-1]),)

    def execute(self, inputs, request):
        import time

        delay_us = int(np.asarray(inputs["DELAY_US"]).reshape(-1)[0])
        delay_us = max(delay_us, int(request.parameters.get("delay_us", 0)))
        if delay_us:
            time.sleep(delay_us / 1e6)
        return {"OUTPUT0": inputs["INPUT0"]}


class RepeatModel(Model):
    """Decoupled model: one request with IN int32[N] produces N streamed
    responses of one element each, the i-th delayed by DELAY[i] usec; WAIT
    delays stream start (mirror of the reference's repeat_int32 model driven
    by simple_grpc_custom_repeat.py:78-105)."""

    name = "repeat_int32"
    platform = "python"
    backend = "python"
    max_batch_size = 0
    decoupled = True
    inputs = (
        TensorSpec("IN", "INT32", [-1]),
        TensorSpec("DELAY", "UINT32", [-1]),
        TensorSpec("WAIT", "UINT32", [1]),
    )
    outputs = (TensorSpec("OUT", "INT32", [1]),)

    def execute_stream(self, inputs, request):
        import time

        values = np.asarray(inputs["IN"]).reshape(-1)
        delays = np.asarray(inputs["DELAY"]).reshape(-1)
        wait_us = int(np.asarray(inputs["WAIT"]).reshape(-1)[0])
        if wait_us:
            time.sleep(wait_us / 1e6)
        for i, value in enumerate(values):
            delay_us = int(delays[i]) if i < len(delays) else 0
            if delay_us:
                time.sleep(delay_us / 1e6)
            yield {"OUT": np.array([value], dtype=np.int32)}
