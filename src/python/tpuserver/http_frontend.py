"""HTTP/REST frontend: serves the KServe-v2 protocol (with the binary-tensor
extension) over a threaded socket server, delegating to
``tpuserver.core.InferenceServer``.

The request plumbing is hand-rolled rather than ``BaseHTTPRequestHandler``:
the stdlib handler parses headers through the email package (~300us per
request) and writes status/headers/body in separate syscalls; at the
quick-start benchmark's ~700us round trip that is most of the budget.
The framing itself (byte-split header parsing, one-``write`` responses,
chunked SSE) lives in ``tpuserver._http_base.BaseHttpHandler``, shared
with the fleet router — this module owns only the replica's route
table (role of the reference server's C++ evhtp frontend on the
latency-critical path)."""

import gzip
import json
import re
import socketserver
import threading
import zlib
from urllib.parse import unquote

import numpy as np

from tpuserver._http_base import BaseHttpHandler, ClientGone
from tpuserver.tensor_io import (
    array_from_binary as _array_from_binary,
    binary_from_array as _binary_from_array,
)
from tpuserver.core import (
    InferenceServer,
    InferRequest,
    RequestedOutput,
    ServerError,
)
from tritonclient.utils import triton_to_np_dtype

_MODEL_URI = re.compile(
    r"^/v2/models/(?P<model>[^/]+)(/versions/(?P<version>[^/]+))?"
    r"(?P<rest>/.*)?$"
)
_SHM_URI = re.compile(
    r"^/v2/(?P<kind>systemsharedmemory|cudasharedmemory|xlasharedmemory)"
    r"(/region/(?P<region>[^/]+))?/(?P<verb>status|register|unregister)$"
)
_REPO_URI = re.compile(
    r"^/v2/repository(/models/(?P<model>[^/]+)/(?P<verb>load|unload)|/index)$"
)
_KVEXPORT_URI = re.compile(
    r"^/v2/kvexport/(?P<gen>[^/]+)(?P<release>/release)?$"
)


def _array_from_json_data(data, datatype, shape):
    if datatype == "BYTES":
        flat = []
        stack = [data]
        while stack:
            item = stack.pop()
            if isinstance(item, list):
                stack.extend(reversed(item))
            else:
                flat.append(
                    item.encode("utf-8") if isinstance(item, str) else item
                )
        return np.array(flat, dtype=np.object_).reshape(shape)
    np_dtype = triton_to_np_dtype(datatype)
    return np.asarray(data, dtype=np_dtype).reshape(shape)


class _Handler(BaseHttpHandler):
    """The replica's route table over the shared framing: every
    request executes against the local ``InferenceServer``."""

    server_token = b"tpu-triton-server"

    @property
    def core(self):
        return self.server.core

    def _dispatch(self, method):
        try:
            self._route(method)
        except ServerError as e:
            headers = None
            if getattr(e, "retry_after", None) is not None:
                # overload shedding contract: 429/503 carry Retry-After
                # so retrying clients back off instead of hammering
                headers = {"Retry-After": int(e.retry_after)}
            self._send_error_json(str(e), e.code, headers)
        except ValueError as e:
            self._send_error_json("malformed request: {}".format(e), 400)
        except (BrokenPipeError, ConnectionResetError, ClientGone):
            raise  # dead socket (incl. injected drops): handle() ends it
        except Exception as e:  # pragma: no cover
            self._send_error_json("internal error: {}".format(e), 500)

    def _send_metrics(self, core):
        """Prometheus exposition (role of Triton's :8002/metrics;
        scraped by perf_analyzer --collect-metrics, reference
        metrics_manager.h:44-91).  The snapshot itself is the core's
        ``metrics_text()`` — the nv_* compatibility families plus the
        tpu_* registry (docs/observability.md) — so the HTTP route and
        the gRPC ServerMetrics unary serve identical bytes."""
        self._send(
            200, core.metrics_text().encode("utf-8"),
            content_type="text/plain")

    def _route(self, method):
        path = self.path.split("?", 1)[0]
        core = self.core

        if path == "/v2/health/live":
            return self._send(200)
        if path == "/v2/health/ready":
            # real readiness (starting/draining/watchdog-tripped all
            # report 503), not a constant — load balancers route on this
            return self._send(200 if core.server_ready() else 503)
        if path == "/v2/health/stats":
            # cheap routing-signal snapshot (lifecycle + scheduler
            # counters, no per-model inference statistics): what the
            # fleet router's prober polls at sub-second cadence
            return self._send_json(core.health_snapshot())
        if path == "/v2" or path == "/v2/":
            return self._send_json(core.server_metadata())
        if path == "/v2/models/stats":
            return self._send_json(core.model_statistics())
        if path == "/metrics":
            return self._send_metrics(core)
        if path == "/v2/logging":
            if method == "POST":
                return self._send_json(
                    core.update_log_settings(json.loads(self._read_body()))
                )
            return self._send_json(core.get_log_settings())
        if path == "/v2/trace/setting":
            if method == "POST":
                return self._send_json(
                    core.update_trace_settings(
                        None, json.loads(self._read_body())
                    )["settings"]
                )
            return self._send_json(core.get_trace_settings()["settings"])

        m = _KVEXPORT_URI.match(path)
        if m:
            # disaggregated transfer control plane: GET hands out the
            # one-shot wire descriptor of a prefill leg's KV export
            # (typed 404 when gone, 409 when already claimed); POST
            # .../release drops it (idempotent) once the decode leg
            # admitted — or never, and the replay TTL sweep reaps it
            gen_id = unquote(m.group("gen"))
            if m.group("release"):
                if method != "POST":
                    raise ServerError(
                        "kvexport release requires POST", code=405)
                core.drop_kv_region(gen_id)
                return self._send_json({})
            if method != "GET":
                raise ServerError(
                    "kvexport descriptor fetch requires GET", code=405)
            return self._send_json(core.kv_export_descriptor(gen_id))

        m = _REPO_URI.match(path)
        if m:
            if m.group("verb") == "load":
                core.load_model(unquote(m.group("model")))
                return self._send_json({})
            if m.group("verb") == "unload":
                unload_dependents = False
                body = self._read_body()
                if body:
                    params = json.loads(body).get("parameters", {})
                    unload_dependents = params.get("unload_dependents", False)
                core.unload_model(unquote(m.group("model")), unload_dependents)
                return self._send_json({})
            return self._send_json(core.repository_index())

        m = _SHM_URI.match(path)
        if m:
            return self._route_shm(m)

        m = _MODEL_URI.match(path)
        if m:
            model = unquote(m.group("model"))
            version = m.group("version") or ""
            rest = m.group("rest") or ""
            if rest == "/ready":
                if core.model_ready(model, version):
                    return self._send(200)
                return self._send(400)
            if rest == "" or rest == "/":
                return self._send_json(core.model_metadata(model, version))
            if rest == "/config":
                return self._send_json(core.model_config(model, version))
            if rest == "/stats":
                return self._send_json(core.model_statistics(model, version))
            if rest == "/trace/setting":
                if method == "POST":
                    return self._send_json(
                        core.update_trace_settings(
                            model, json.loads(self._read_body())
                        )["settings"]
                    )
                return self._send_json(
                    core.get_trace_settings(model)["settings"]
                )
            if rest == "/infer" and method == "POST":
                return self._route_infer(model, version)
            if rest in ("/generate", "/generate_stream") and method == "POST":
                return self._route_generate(
                    model, version, stream=rest.endswith("_stream")
                )
        raise ServerError("unknown endpoint: " + path, code=404)

    def _route_shm(self, m):
        core = self.core
        kind = m.group("kind")
        region = unquote(m.group("region")) if m.group("region") else ""
        verb = m.group("verb")
        if kind == "systemsharedmemory":
            if verb == "status":
                return self._send_json(core.system_shm_status(region))
            if verb == "register":
                req = json.loads(self._read_body())
                core.register_system_shm(
                    region, req["key"], req.get("offset", 0), req["byte_size"]
                )
                return self._send_json({})
            core.unregister_system_shm(region)
            return self._send_json({})
        if kind == "cudasharedmemory":
            if verb == "status":
                return self._send_json(core.cuda_shm_status(region))
            if verb == "register":
                req = json.loads(self._read_body())
                core.register_cuda_shm(
                    region, req.get("raw_handle", {}).get("b64", ""),
                    req.get("device_id", 0), req["byte_size"],
                )
                return self._send_json({})
            core.unregister_cuda_shm(region)
            return self._send_json({})
        # xlasharedmemory
        if verb == "status":
            return self._send_json(core.xla_shm_status(region))
        if verb == "register":
            req = json.loads(self._read_body())
            core.register_xla_shm(
                region, req.get("raw_handle", {}).get("b64", ""),
                req.get("device_ordinal", 0), req["byte_size"],
            )
            return self._send_json({})
        core.unregister_xla_shm(region)
        return self._send_json({})

    # -- generate (decoupled streaming over HTTP) -------------------------

    def _route_generate(self, model, version, stream):
        """KServe-style generate endpoints for decoupled models.

        The request body is the infer JSON shape (``inputs`` with
        ``data``, optional ``parameters``).  ``/generate`` collects the
        whole decoupled burst into one JSON response (each output's
        per-step values concatenated along a leading step axis);
        ``/generate_stream`` emits one SSE event per decoupled response
        over a chunked transfer — the HTTP fan-out of the continuous-
        batching scheduler's per-step tokens (each chunk leaves as soon
        as its decode step retires, so concurrent requests on separate
        connections interleave at token granularity).
        """
        core = self.core
        body = self._read_body()
        request_json = json.loads(body)
        parameters = dict(request_json.get("parameters", {}))
        if stream:
            # SSE-standard reconnection: a client that lost its
            # connection re-POSTs the same body with Last-Event-ID
            # "<generation_id>/<seq>"; the scheduler replays from
            # seq + 1 and splices the live continuation
            last_id = self.headers.get("Last-Event-ID")
            if last_id:
                # LAST slash: a client-chosen generation_id may itself
                # contain '/' (e.g. "tenant/abc"); the seq is always
                # the final segment
                gen_id, sep, seq = last_id.rpartition("/")
                if sep and gen_id:
                    try:
                        parameters.setdefault(
                            "resume_from_seq", int(seq) + 1)
                        parameters.setdefault(
                            "resume_generation_id", gen_id)
                    except ValueError:
                        pass  # malformed id: treat as a fresh request
        inputs = {}
        shm_input_regions = []
        for tin in request_json.get("inputs", []):
            datatype = tin.get("datatype")
            if not datatype:
                raise ServerError(
                    "generate input '{}' needs a datatype".format(
                        tin.get("name"))
                )
            tparams = tin.get("parameters", {})
            if "shared_memory_region" in tparams:
                # generation admissions accept PROMPT_IDS (and any
                # other input) by shm region reference: resolved
                # through the same bounds-checked core path as /infer;
                # for an in-process XLA region the model consumes the
                # device segment view directly — zero host staging
                inputs[tin["name"]] = core.read_shm_input(
                    tparams["shared_memory_region"],
                    tparams.get("shared_memory_byte_size", 0),
                    tparams.get("shared_memory_offset", 0),
                    datatype,
                    tin["shape"],
                )
                shm_input_regions.append(tparams["shared_memory_region"])
            else:
                inputs[tin["name"]] = _array_from_json_data(
                    tin.get("data"), datatype, tin["shape"]
                )
        request = InferRequest(
            model, version, request_json.get("id", ""), inputs, None,
            parameters,
        )
        # the model pins these for the stream's lifetime: the region
        # backing a live device view must conflict on unregister (409)
        request.shm_input_regions = tuple(shm_input_regions)

        def response_json(resp):
            out = {
                "model_name": resp.model_name,
                "model_version": resp.model_version,
                "outputs": [],
            }
            if resp.id:
                out["id"] = resp.id
            for spec, array, _ in resp.outputs:
                entry = dict(spec)
                if array is not None:
                    entry["data"] = (
                        [v.decode("utf-8", errors="replace")
                         if isinstance(v, bytes) else str(v)
                         for v in array.reshape(-1)]
                        if spec["datatype"] == "BYTES"
                        else array.reshape(-1).tolist()
                    )
                out["outputs"].append(entry)
            return out

        if not stream:
            merged = None
            for resp in core.infer_stream(request):
                piece = response_json(resp)
                if merged is None:
                    merged = piece
                    for entry in merged["outputs"]:
                        entry["shape"] = [1] + list(entry["shape"])
                else:
                    by_name = {e["name"]: e for e in merged["outputs"]}
                    for entry in piece["outputs"]:
                        tgt = by_name.get(entry["name"])
                        if tgt is None:
                            merged["outputs"].append(entry)
                            entry["shape"] = [1] + list(entry["shape"])
                        else:
                            tgt["data"].extend(entry["data"])
                            tgt["shape"][0] += 1
            if merged is None:
                merged = {"model_name": model, "model_version": version,
                          "outputs": []}
            return self._send_json(merged)

        # SSE over chunked transfer: the stream must start before the
        # generation finishes, so errors after the first token arrive
        # in-band as an {"error": ...} event (the status line is gone)
        from tpuserver import faults as _faults

        try:
            for resp in core.infer_stream(request):
                self._ensure_started()
                payload = response_json(resp)
                event = b""
                if resp.parameters:
                    wire = {k: v for k, v in resp.parameters.items()
                            if not k.startswith("triton_")}
                    if wire:
                        payload["parameters"] = wire
                    gen_id = resp.parameters.get("generation_id")
                    seq = resp.parameters.get("seq")
                    if gen_id is not None and seq is not None:
                        # the SSE id the browser/client hands back as
                        # Last-Event-ID on reconnect
                        event += "id: {}/{}\n".format(
                            gen_id, seq).encode("utf-8")
                # chaos hook: sever the connection mid-stream (no
                # terminal chunk) so client auto-resume is drivable
                # end-to-end; skip=N drops after the Nth event
                _faults.fire("http.generate_stream", core.fault_scope)
                self._send_chunk(
                    event + b"data: "
                    + json.dumps(payload).encode("utf-8")
                    + b"\n\n"
                )
        except _faults.FaultInjected:
            try:
                self.connection.close()
            finally:
                raise BrokenPipeError("injected mid-stream disconnect")
        except ServerError as e:
            if not self._started:
                raise
            self._send_chunk(
                b"data: " + json.dumps({"error": str(e)}).encode("utf-8")
                + b"\n\n"
            )
            self._end_chunks()
            return
        self._ensure_started()
        # explicit terminal event: a premature TCP close mid-chunked
        # stream is NOT reliably distinguishable from a clean end by
        # every HTTP client (stdlib line iteration just stops), so
        # completion is in-band — a stream that ends WITHOUT this
        # marker (or an error event) was dropped, and resuming clients
        # reconnect with Last-Event-ID
        self._send_chunk(b'data: {"final": true}\n\n')
        self._end_chunks()

    # -- inference --------------------------------------------------------

    def _route_infer(self, model, version):
        core = self.core
        body = self._read_body()
        header_length = self.headers.get("Inference-Header-Content-Length")
        if header_length is not None:
            json_len = int(header_length)
            request_json = json.loads(body[:json_len])
            binary = body[json_len:]
        else:
            request_json = json.loads(body)
            binary = b""

        parameters = dict(request_json.get("parameters", {}))
        binary_all_outputs = parameters.pop("binary_data_output", False)

        declared_in = None  # resolved lazily: most clients send datatypes

        inputs = {}
        offset = 0
        for tin in request_json.get("inputs", []):
            name = tin["name"]
            datatype = tin.get("datatype")
            if not datatype:
                if declared_in is None:
                    try:
                        model_meta = core.model_metadata(model, version)
                    except ServerError:
                        model_meta = {"inputs": []}
                    declared_in = {
                        t["name"]: t for t in model_meta.get("inputs", [])
                    }
                datatype = declared_in.get(name, {}).get("datatype")
            shape = tin["shape"]
            tparams = tin.get("parameters", {})
            if "shared_memory_region" in tparams:
                inputs[name] = core.read_shm_input(
                    tparams["shared_memory_region"],
                    tparams.get("shared_memory_byte_size", 0),
                    tparams.get("shared_memory_offset", 0),
                    datatype,
                    shape,
                )
            elif "binary_data_size" in tparams:
                size = tparams["binary_data_size"]
                raw = binary[offset : offset + size]
                offset += size
                inputs[name] = _array_from_binary(raw, datatype, shape)
            elif "data" in tin:
                inputs[name] = _array_from_json_data(
                    tin["data"], datatype, shape
                )
            else:
                raise ServerError(
                    "input '{}' has no data and no shared-memory "
                    "reference".format(name)
                )

        requested = None
        if "outputs" in request_json:
            requested = []
            for tout in request_json["outputs"]:
                oparams = tout.get("parameters", {})
                requested.append(
                    RequestedOutput(
                        tout["name"],
                        binary_data=oparams.get("binary_data", False)
                        or binary_all_outputs,
                        class_count=oparams.get("classification", 0),
                        shm_region=oparams.get("shared_memory_region"),
                        shm_byte_size=oparams.get(
                            "shared_memory_byte_size", 0
                        ),
                        shm_offset=oparams.get("shared_memory_offset", 0),
                    )
                )

        request = InferRequest(
            model,
            version,
            request_json.get("id", ""),
            inputs,
            requested,
            parameters,
        )
        response = core.infer(request)

        # Assemble response: JSON header + binary section.
        out_json = {
            "model_name": response.model_name,
            "model_version": response.model_version,
            "outputs": [],
        }
        if response.id:
            out_json["id"] = response.id
        binary_parts = []
        for spec, array, delivery in response.outputs:
            entry = dict(spec)
            oparams = {}
            if array is None:
                oparams["shared_memory_region"] = delivery["shm_region"]
                oparams["shared_memory_byte_size"] = delivery["shm_byte_size"]
                if delivery["shm_offset"]:
                    oparams["shared_memory_offset"] = delivery["shm_offset"]
            elif (requested is not None and delivery["binary_data"]) or (
                requested is None and binary_all_outputs
            ):
                raw = _binary_from_array(array, spec["datatype"])
                oparams["binary_data_size"] = len(raw)
                binary_parts.append(raw)
            else:
                if spec["datatype"] == "BYTES":
                    entry["data"] = [
                        v.decode("utf-8", errors="replace")
                        if isinstance(v, bytes)
                        else str(v)
                        for v in array.reshape(-1)
                    ]
                elif spec["datatype"] == "BF16":
                    raise ServerError(
                        "BF16 outputs require binary_data=true"
                    )
                else:
                    entry["data"] = array.reshape(-1).tolist()
            if oparams:
                entry["parameters"] = oparams
            out_json["outputs"].append(entry)

        header = json.dumps(out_json).encode("utf-8")
        headers = {}
        if binary_parts:
            payload = header + b"".join(binary_parts)
            headers["Inference-Header-Content-Length"] = str(len(header))
            content_type = "application/octet-stream"
        else:
            payload = header
            content_type = "application/json"

        accept_encoding = self.headers.get("Accept-Encoding", "")
        if "gzip" in accept_encoding:
            payload = gzip.compress(payload)
            headers["Content-Encoding"] = "gzip"
        elif "deflate" in accept_encoding:
            payload = zlib.compress(payload)
            headers["Content-Encoding"] = "deflate"
        self._send(200, payload, headers, content_type)


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class HttpFrontend:
    """Threaded HTTP server wrapper: ``start()``/``stop()``; ``port`` is
    resolved after start (pass 0 to pick a free port)."""

    def __init__(self, core, host="127.0.0.1", port=0, verbose=False):
        self._core = core
        self._httpd = _Server((host, port), _Handler)
        self._httpd.core = core
        self._httpd.verbose = verbose
        self._thread = None

    @property
    def port(self):
        return self._httpd.server_address[1]

    @property
    def url(self):
        return "{}:{}".format(self._httpd.server_address[0], self.port)

    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.05},
            daemon=True,
        )
        self._thread.start()
        self._core.attach_frontend()
        self._attached = True
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if getattr(self, "_attached", False):
            # only an attach that actually happened may detach (see
            # grpc_frontend.stop)
            self._attached = False
            self._core.detach_frontend()
