"""Wire-tensor (de)serialization shared by the HTTP and gRPC frontends:
numpy <-> raw bytes for every KServe-v2 datatype incl. BYTES (4-byte length
prefix) and BF16 (native ml_dtypes)."""

import numpy as np

from tpuserver.core import ServerError
from tritonclient.utils import (
    deserialize_bf16_tensor,
    deserialize_bytes_tensor,
    serialize_bf16_tensor,
    serialize_byte_tensor,
    triton_to_np_dtype,
)


def binary_from_array(array, datatype):
    if datatype == "BYTES":
        serialized = serialize_byte_tensor(array)
        return serialized.item() if serialized.size > 0 else b""
    if datatype == "BF16":
        serialized = serialize_bf16_tensor(array)
        return serialized.item() if serialized.size > 0 else b""
    return np.ascontiguousarray(array).tobytes()


def array_from_binary(raw, datatype, shape):
    if datatype == "BYTES":
        return deserialize_bytes_tensor(raw).reshape(shape)
    if datatype == "BF16":
        return deserialize_bf16_tensor(raw).reshape(shape)
    np_dtype = triton_to_np_dtype(datatype)
    if np_dtype is None:
        raise ServerError("unsupported datatype " + str(datatype))
    return np.frombuffer(raw, dtype=np_dtype).reshape(shape)
