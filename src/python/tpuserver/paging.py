"""Paged KV allocation + radix prefix caching (host-side bookkeeping).

The continuous-batching scheduler's KV cache used to be *slotted*:
``max_slots`` contiguous full-length rows, so capacity was fixed at
slot granularity and every admission re-prefilled its whole prompt.
This module holds the two host-side structures that turn the cache
into a *paged* pool (vLLM's PagedAttention shape) with cross-request
prefix reuse (SGLang's RadixAttention shape):

- :class:`PageAllocator` — a free list over ``n_pages`` fixed-size
  pages.  Admission reserves its whole potential span up front
  (prompt + max_tokens, minus any shared prefix), so a generation can
  never OOM mid-decode: exhaustion is a typed admission-time signal,
  not a crash.
- :class:`RadixPrefixCache` — a page-granular radix tree (each node
  owns ONE physical page and is keyed by that page's ``page_size``
  token ids).  Streams sharing a prompt prefix share the prefix's
  physical pages (ref-counted while any live stream uses them);
  retired streams donate their full pages back as *cached* entries
  that later admissions hit instead of re-prefilling.  Unreferenced
  branches evict LRU, leaves first, when the allocator runs short.

Content addressing makes sharing safe: a page's K/V is a
deterministic function of the token ids at its positions (greedy
decode, absolute-position RoPE), so two prompts with identical token
prefixes have bitwise-identical prefix KV — the same invariant
supervised restart and cross-replica handoff already rely on.

Everything here is pure host bookkeeping — the decode loop thread is
the only mutator, device arrays never enter this module.  ``stats``
readers on other threads only see plain-int counters (atomic loads in
CPython), never an iterating view.
"""

from collections import deque

__all__ = ["PageAllocator", "RadixPrefixCache", "pages_for"]


def pages_for(length, page_size):
    """Pages needed to span ``length`` token positions."""
    return -(-int(length) // int(page_size)) if length > 0 else 0


class PageAllocator:
    """Free-list allocator over ``n_pages`` physical KV pages.

    ``alloc`` is all-or-nothing: a partial grant would leave the
    caller holding pages it cannot use (the admission span is one
    unit).  Page id ``n_pages`` is the scatter *sentinel* — the
    device-side ``mode="drop"`` index — and is never handed out.
    """

    def __init__(self, n_pages, page_size):
        if n_pages < 1:
            raise ValueError(
                "need at least one KV page (got {})".format(n_pages))
        if page_size < 1:
            raise ValueError(
                "page_size must be >= 1 (got {})".format(page_size))
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self._free = deque(range(self.n_pages))

    @property
    def free_count(self):
        return len(self._free)

    def alloc(self, n):
        """``n`` page ids, or None when the free list is short (the
        caller evicts from the radix cache and retries, then sheds)."""
        if n > len(self._free):
            return None
        return [self._free.popleft() for _ in range(n)]

    def free(self, ids):
        for page in ids:
            self._free.append(page)


class _RadixNode:
    __slots__ = ("parent", "key", "page", "children", "ref", "last_used")

    def __init__(self, parent, key, page):
        self.parent = parent
        self.key = key          # tuple of page_size token ids
        self.page = page        # physical page id
        self.children = {}      # key tuple -> _RadixNode
        self.ref = 0            # live streams holding this page
        self.last_used = 0      # logical LRU clock stamp


class RadixPrefixCache:
    """Page-granular radix tree over token-id sequences.

    A node at depth ``d`` (root is depth 0, holds no page) owns the
    physical page whose positions are ``[(d-1)*page_size,
    d*page_size)`` for every sequence whose first ``d`` pages of
    tokens match the root-to-node path.  Only loop-thread mutation;
    the plain-int ``pages``/``unreferenced`` counters are safe for
    racy stats reads.
    """

    def __init__(self, page_size):
        self.page_size = int(page_size)
        self._root = _RadixNode(None, None, None)
        self._clock = 0
        self.pages = 0          # nodes (= cached+pinned pages) in the tree
        self.unreferenced = 0   # nodes with ref == 0 (pure cache)
        # bumped on every structural change (insert adds a node, evict
        # removes one): read-only consumers holding derived indices
        # over the tree's content — the speculative n-gram drafter —
        # compare it to decide when to rebuild.  Plain int: safe for
        # racy reads like the other counters.
        self.version = 0

    # -- lookup / pinning --------------------------------------------------

    def _tick(self):
        self._clock += 1
        return self._clock

    def match(self, tokens):
        """Longest page-aligned prefix of ``tokens`` present in the
        tree: ``(path_nodes, page_ids)`` — empty lists on a cold
        miss.  Does NOT pin; call :meth:`acquire` on the path before
        any operation that could evict."""
        p = self.page_size
        node = self._root
        path = []
        for d in range(len(tokens) // p):
            key = tuple(int(t) for t in tokens[d * p:(d + 1) * p])
            child = node.children.get(key)
            if child is None:
                break
            path.append(child)
            node = child
        return path, [n.page for n in path]

    def continuation(self, tokens, limit):
        """Cached continuation of the EXACT sequence ``tokens``: up to
        ``limit`` token ids that previously-served sequences decoded
        after this precise root-anchored context, or ``[]`` when the
        context isn't cached that deep.

        This is what makes the tree a draft model and not just a KV
        store: for regenerate/extend traffic the live context is a
        prefix of a donated sequence, and the exact-prefix walk is
        unambiguous where any fixed-length n-gram is not (a run of
        repeated tokens collides every n-gram key, but only one tree
        path spells the full context).  Where the tree branches, the
        most recently used child wins — recency is the same signal
        LRU eviction trusts.

        STRICTLY read-only: no pinning, no ref-count changes, no LRU
        stamping (same contract as :meth:`iter_sequences`)."""
        path, _ = self.match(tokens)
        node = path[-1] if path else self._root
        rem = [int(t) for t in tokens[len(path) * self.page_size:]]
        out = []
        while len(out) < limit:
            best = None
            for child in node.children.values():
                if (list(child.key[:len(rem)]) == rem
                        and (best is None
                             or child.last_used > best.last_used)):
                    best = child
            if best is None:
                break
            out.extend(best.key[len(rem):])
            node, rem = best, []
        return out[:limit]

    def acquire(self, nodes):
        """Pin ``nodes`` (one ref each) so eviction cannot free pages
        a live stream's page table points at."""
        stamp = self._tick()
        for node in nodes:
            if node.ref == 0:
                self.unreferenced -= 1
            node.ref += 1
            node.last_used = stamp

    def release(self, nodes):
        for node in nodes:
            node.ref -= 1
            if node.ref == 0:
                self.unreferenced += 1
                node.last_used = self._tick()

    def iter_sequences(self):
        """Yield every root-to-leaf token sequence in the tree, as a
        flat list of ints (page keys concatenated in path order).

        STRICTLY read-only: no pinning, no ref-count changes, no LRU
        stamping — the speculative drafter walks cached content
        without affecting what eviction may reclaim.  Caller must not
        mutate the tree mid-iteration (the decode loop is the only
        mutator, and it drives both)."""
        stack = [(self._root, [])]
        while stack:
            node, prefix = stack.pop()
            if node is not self._root:
                prefix = prefix + list(node.key)
            if not node.children:
                if prefix:
                    yield prefix
                continue
            for child in node.children.values():
                stack.append((child, prefix))

    # -- insertion ---------------------------------------------------------

    def insert_tail(self, path, tokens, start_page, owned_ids, pin):
        """Extend the tree below ``path`` (the already-matched node
        list, possibly empty) with the full pages of ``tokens`` from
        logical page ``start_page``, adopting pages from ``owned_ids``
        (``owned_ids[i]`` is logical page ``start_page + i``).

        A page whose key already exists in the tree is a concurrent
        duplicate: the existing node wins and the owned page is
        surrendered.  Returns ``(new_path_nodes, dup_entries,
        freed_ids)`` where ``dup_entries`` is ``[(logical_page,
        existing_page_id), ...]`` — the caller repoints its page
        table — and ``freed_ids`` are the surrendered owned pages.
        With ``pin`` the whole appended path (new and duplicate nodes
        alike) is acquired."""
        p = self.page_size
        node = path[-1] if path else self._root
        stamp = self._tick()
        appended = []
        dups = []
        freed = []
        for i, page in enumerate(owned_ids):
            d = start_page + i
            lo, hi = d * p, (d + 1) * p
            if hi > len(tokens):
                raise ValueError(
                    "insert_tail past the known token prefix "
                    "(page {} needs tokens [{}:{}), have {})".format(
                        d, lo, hi, len(tokens)))
            key = tuple(int(t) for t in tokens[lo:hi])
            child = node.children.get(key)
            if child is None:
                child = _RadixNode(node, key, page)
                child.last_used = stamp
                node.children[key] = child
                self.pages += 1
                self.unreferenced += 1
                self.version += 1
            else:
                dups.append((d, child.page))
                freed.append(page)
            appended.append(child)
            node = child
        if pin:
            self.acquire(appended)
        return appended, dups, freed

    # -- eviction ----------------------------------------------------------

    def evict(self, n):
        """Free up to ``n`` pages by removing unpinned leaves in LRU
        order (leaves first keeps every surviving node's path
        intact).  One tree walk seeds a min-heap of evictable leaves;
        a parent whose last child evicts becomes evictable and joins
        the heap — O(tree + n log n), not a re-walk per page (the
        admission path calls this under thrash).  Returns the freed
        page ids — shorter than ``n`` when everything left is
        pinned."""
        if n <= 0:
            return []
        import heapq

        heap = []
        stack = [self._root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if (node is not self._root and not node.children
                    and node.ref == 0):
                heapq.heappush(heap, (node.last_used, id(node), node))
        freed = []
        while heap and len(freed) < n:
            _, _, victim = heapq.heappop(heap)
            parent = victim.parent
            del parent.children[victim.key]
            victim.parent = None
            self.pages -= 1
            self.unreferenced -= 1
            self.version += 1
            freed.append(victim.page)
            if (parent is not self._root and not parent.children
                    and parent.ref == 0):
                heapq.heappush(heap, (parent.last_used, id(parent),
                                      parent))
        return freed
