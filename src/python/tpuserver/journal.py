"""Crash-durable generation journal for the fleet router.

PR 7's hardening note (iv) conceded the front tier's one durability
hole: every sticky binding, handoff offset rebase, and replay buffer
lives only in the router process's heap, so a RESTARTED router must
answer a handoff-marked resume (``gen~offset/seq``) with a typed 404 —
the offset map that would make the replay point meaningful is gone.
This module closes the hole with an **append-only record log** of the
router's resume-critical state:

- ``bind``    — a generation's identity: id, request path, the original
  request JSON (the handoff re-prefill source), and its first home.
- ``home``    — a (re)homing: the owning replica url and the current
  handoff offset (router seq = offset + backend seq).
- ``ev``      — one relayed SSE event: router seq, the exact ``id:``
  line the client saw (epoch marker included), and the payload.  The
  per-generation relayed-seq watermark is implicit in the highest seq.
- ``fin`` / ``drop`` — terminal outcomes.

**Wire format.**  Each record is framed ``<u32 length><u32 crc32>``
followed by ``length`` bytes of UTF-8 JSON.  Frames are the recovery
contract: a half-written final record (torn write at crash) fails its
length or checksum and is **truncated, never fatal** — recovery keeps
every complete record before it.

**Hot-path contract.**  The relay loop only *enqueues*: `append` is a
single ``collections.deque.append`` (GIL-atomic, lock-free — the
bounded deque drops the oldest enqueued record under backpressure
rather than ever blocking a token relay).  A dedicated writer thread
drains the queue in batches, frames + writes + fsyncs, and owns every
file handle.  The event path therefore acquires **zero new locks**
(test-pinned via AST inspection in tests/test_router_ha.py).

**Segment rotation.**  The log lives in a directory of
``seg-<n>.log`` files.  The writer rotates to a fresh segment every
``rotate_interval_s`` (align it with the router's generation TTL) and
retains the newest ``retain_segments`` — records older than the TTL
window describe generations no resume can name anymore, so dropping
whole expired segments bounds the disk footprint without per-record
compaction.

Readers: :func:`read_journal` replays every retained record at boot
(``FleetRouter(journal=...)`` recovery), and :class:`JournalFollower`
tails the directory incrementally (the ``--standby`` router's warm
copy).  See docs/resilience.md "Router HA & state durability".
"""

import json
import os
import re
import struct
import threading
import time
import zlib
from collections import deque

__all__ = [
    "JournalFollower",
    "JournalWriter",
    "read_journal",
]

_FRAME = struct.Struct("<II")  # (payload length, crc32(payload))
_SEGMENT_RE = re.compile(r"^seg-(\d+)\.log$")

#: A sanity bound on one record's framed length: a length prefix past
#: it is torn-tail garbage (or a foreign file), never a real record.
_MAX_RECORD_BYTES = 16 * 1024 * 1024


def _segment_index(name):
    m = _SEGMENT_RE.match(name)
    return int(m.group(1)) if m else None


def _list_segments(directory):
    """``[(index, path)]`` of the directory's segments, oldest first."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    out = []
    for name in names:
        idx = _segment_index(name)
        if idx is not None:
            out.append((idx, os.path.join(directory, name)))
    out.sort()
    return out


def _read_records(blob, offset=0):
    """Parse complete records out of ``blob`` starting at ``offset``.

    Returns ``(records, next_offset, clean)``: ``next_offset`` is the
    byte position after the last COMPLETE record, and ``clean`` is
    False when trailing bytes exist that do not frame a complete,
    checksum-valid record — a torn tail (crash mid-write) or
    corruption.  The caller decides whether that tail is "still being
    written" (follower: retry later) or "truncate and move on"
    (recovery)."""
    records = []
    n = len(blob)
    pos = offset
    while pos + _FRAME.size <= n:
        length, crc = _FRAME.unpack_from(blob, pos)
        if length > _MAX_RECORD_BYTES:
            return records, pos, False
        end = pos + _FRAME.size + length
        if end > n:
            return records, pos, False  # incomplete tail
        payload = blob[pos + _FRAME.size:end]
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            return records, pos, False  # torn/corrupt record
        try:
            records.append(json.loads(payload))
        except ValueError:
            return records, pos, False
        pos = end
    return records, pos, pos == n


def read_journal(directory):
    """Replay every retained record, oldest segment first.

    Returns ``(records, truncated)``: ``truncated`` counts segments
    whose tail did not parse — a torn final write is expected after a
    crash (the final segment), and recovery simply keeps the clean
    prefix.  A missing or empty directory recovers to nothing, not an
    error (a first boot with ``--journal`` pointing at a fresh
    directory must just work)."""
    records = []
    truncated = 0
    for _idx, path in _list_segments(directory):
        try:
            with open(path, "rb") as fh:
                blob = fh.read()
        except OSError:
            truncated += 1
            continue
        segment_records, _pos, clean = _read_records(blob)
        records.extend(segment_records)
        if not clean:
            truncated += 1
    return records, truncated


class JournalWriter:
    """The append side: a bounded lock-free queue drained by one
    dedicated writer thread.

    Parameters
    ----------
    directory : str
        The journal directory (created if missing).  The writer always
        opens a FRESH segment — it never appends to a predecessor's
        file, so a torn tail from a crashed writer stays where
        recovery already truncated it.
    rotate_interval_s : float
        Segment rotation cadence.  Align with the router's generation
        TTL: a dropped segment then only ever drops records no resume
        can name.
    retain_segments : int
        Newest segments kept on rotation (>= 2 so the retained span
        always covers at least one full rotation interval).
    flush_interval_s : float
        Writer wake cadence; also the crash-loss upper bound for
        enqueued-but-unwritten records.
    queue_capacity : int
        Bounded queue depth; overflow drops the OLDEST enqueued record
        (durability degrades, the token relay never blocks).
    """

    def __init__(self, directory, rotate_interval_s=60.0,
                 retain_segments=3, flush_interval_s=0.02,
                 queue_capacity=65536, clock=None):
        self._dir = directory
        os.makedirs(directory, exist_ok=True)
        self._rotate_interval_s = float(rotate_interval_s)
        self._retain_segments = max(2, int(retain_segments))
        self._flush_interval_s = float(flush_interval_s)
        # the hot-path queue: deque.append/popleft are GIL-atomic, so
        # the relay loop enqueues without acquiring ANY lock; maxlen
        # makes overflow drop-oldest instead of blocking
        self._queue = deque(maxlen=int(queue_capacity))
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._records = 0       # guarded-by: _lock
        self._bytes = 0         # guarded-by: _lock
        self._fsyncs = 0        # guarded-by: _lock
        self._drain_passes = 0  # guarded-by: _lock
        self._closed = False    # guarded-by: _lock
        segments = _list_segments(directory)
        self._next_index = (segments[-1][0] + 1) if segments else 1
        self._fh = None                 # writer-thread-owned
        self._segment_started = None    # writer-thread-owned
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="router-journal-writer", daemon=True)
        self._thread.start()

    # -- hot path ----------------------------------------------------------

    def append(self, record):
        """Enqueue one record dict.  Lock-free (a single deque append);
        encoding, framing, and I/O all happen on the writer thread."""
        self._queue.append(record)
        self._wake.set()

    # -- writer thread -----------------------------------------------------

    def _open_segment(self):
        if self._fh is not None:
            self._fh.close()
        path = os.path.join(
            self._dir, "seg-{:08d}.log".format(self._next_index))
        self._next_index += 1
        self._fh = open(path, "ab")
        self._segment_started = self._clock()
        # retention: count-based (restart-safe — no wall-clock ages),
        # newest retain_segments survive
        segments = _list_segments(self._dir)
        for _idx, old in segments[:-self._retain_segments]:
            try:
                os.remove(old)
            except OSError:
                pass

    def _drain(self):
        """Write every queued record as one batch, then fsync once."""
        batch = []
        while True:
            try:
                batch.append(self._queue.popleft())
            except IndexError:
                break
        if not batch:
            with self._lock:
                self._drain_passes += 1
            return
        if (self._fh is None
                or self._clock() - self._segment_started
                >= self._rotate_interval_s):
            self._open_segment()
        frames = []
        for record in batch:
            payload = json.dumps(
                record, separators=(",", ":")).encode("utf-8")
            frames.append(_FRAME.pack(
                len(payload), zlib.crc32(payload) & 0xFFFFFFFF))
            frames.append(payload)
        blob = b"".join(frames)
        self._fh.write(blob)
        self._fh.flush()
        os.fsync(self._fh.fileno())
        with self._lock:
            self._records += len(batch)
            self._bytes += len(blob)
            self._fsyncs += 1
            self._drain_passes += 1

    def _run(self):
        while not self._stop.is_set():
            self._wake.wait(self._flush_interval_s)
            self._wake.clear()
            try:
                self._drain()
            except OSError:
                # a full/readonly disk must degrade durability, never
                # take the serving path down; the next drain retries
                pass
        try:
            self._drain()
        except OSError:
            pass
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    # -- lifecycle / observability -----------------------------------------

    def flush(self, timeout_s=5.0):
        """Block until everything enqueued so far is written + fsynced
        (the SIGTERM-drain path: flush, then exit).  Completion is a
        drain pass that both STARTED after this call and left the
        queue empty — every record enqueued before the call is then
        covered by that pass's (or an earlier) fsync."""
        deadline = time.monotonic() + timeout_s
        with self._lock:
            target = self._drain_passes
        while time.monotonic() < deadline:
            self._wake.set()
            with self._lock:
                passes = self._drain_passes
            if not self._queue and passes > target:
                return True
            time.sleep(0.005)
        return False

    def close(self, timeout_s=5.0):
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=timeout_s)

    def stats(self):
        with self._lock:
            return {
                "records": self._records,
                "bytes": self._bytes,
                "fsyncs": self._fsyncs,
                "queued": len(self._queue),
            }


class JournalFollower:
    """Incremental reader for the standby router: remembers its
    position and yields only complete new records on each
    :meth:`poll`.

    A torn tail is ambiguous while the writer lives — the record may
    simply still be in flight — so the follower retries the same
    offset next poll.  Once a NEWER segment exists the writer has
    moved on and will never complete that tail, so the follower
    abandons it and advances.  (Single-writer discipline: only the
    ACTIVE router writes; a standby promotes only after the active is
    gone.)"""

    def __init__(self, directory):
        self._dir = directory
        self._segment = None   # (index, path)
        self._offset = 0

    def poll(self):
        """Every complete record appended since the last poll."""
        records = []
        while True:
            segments = _list_segments(self._dir)
            if not segments:
                return records
            if self._segment is None:
                self._segment = segments[0]
                self._offset = 0
            current_idx = self._segment[0]
            newer = [s for s in segments if s[0] > current_idx]
            try:
                with open(self._segment[1], "rb") as fh:
                    fh.seek(self._offset)
                    blob = fh.read()
            except OSError:
                blob = b""
            got, consumed, clean = _read_records(blob)
            records.extend(got)
            self._offset += consumed
            if clean and not newer:
                return records
            if not clean and not newer:
                # torn-or-in-flight tail and the writer still owns this
                # segment: retry the same offset next poll
                return records
            # the writer moved to a newer segment: whatever tail this
            # one has will never complete — advance
            self._segment = newer[0]
            self._offset = 0
