"""Canonical typed serving errors — the single definition site.

One class per wire-mapped condition, each carrying its HTTP code (the
gRPC frontend derives its status from the same code).  tpulint rule R4
enforces the contract this module exists for: every subclass here must
appear in the HTTP frontend's ``_STATUS_LINE`` map, the gRPC frontend's
``_status_code`` map, and the status table in ``docs/resilience.md`` —
and **no other module may define a class with the same name** (the
scheduler and core used to carry twin ``SlotQuarantined`` /
``UnknownGeneration`` definitions kept consistent only by convention;
now both import from here).

``tpuserver.core`` re-exports everything for backward compatibility —
``from tpuserver.core import ServerError`` keeps working.
"""

__all__ = [
    "DeadlineExceeded",
    "KvExportConflict",
    "KvExportNotFound",
    "Overloaded",
    "ServerError",
    "ShmRegionInUse",
    "ShuttingDown",
    "SlotQuarantined",
    "UnknownGeneration",
]


class ServerError(Exception):
    """Server-side error carrying an HTTP-ish status code.

    ``retry_after`` (seconds, or None) is advisory: frontends surface it
    as the HTTP ``Retry-After`` header / gRPC ``retry-after`` trailing
    metadata so well-behaved clients back off instead of hammering."""

    def __init__(self, msg, code=400, retry_after=None):
        super().__init__(msg)
        self.code = code
        self.retry_after = retry_after


class DeadlineExceeded(ServerError):
    """The request's deadline (its ``timeout`` parameter, the gRPC
    context deadline, or the scheduler's per-stream bound) expired —
    HTTP 504 / gRPC DEADLINE_EXCEEDED."""

    def __init__(self, msg):
        super().__init__(msg, code=504)


class Overloaded(ServerError):
    """The server shed this request under load (admission queue full or
    in-flight cap reached) — HTTP 429 + Retry-After / gRPC
    RESOURCE_EXHAUSTED.  Retryable by contract."""

    def __init__(self, msg, retry_after=1):
        super().__init__(msg, code=429, retry_after=retry_after)


class ShuttingDown(ServerError):
    """The server is draining or stopped and not accepting new work —
    HTTP 503 / gRPC UNAVAILABLE.  Retryable against another replica."""

    def __init__(self, msg, retry_after=None):
        super().__init__(msg, code=503, retry_after=retry_after)


class SlotQuarantined(ServerError):
    """The request's own generation poisoned its decode slot
    (non-finite logits) and was quarantined; co-batched generations are
    unaffected — HTTP 422 / gRPC INVALID_ARGUMENT.  NOT retryable: the
    request, not the server, is at fault."""

    def __init__(self, msg):
        super().__init__(msg, code=422)


class ShmRegionInUse(ServerError):
    """An unregister named a shared-memory region an in-flight
    generation or registered token ring still references — HTTP 409 /
    gRPC ABORTED.  The region stays registered; retry the unregister
    after the generation finishes (or cancel it first).  Turning this
    race into a typed conflict is what keeps a concurrent unregister
    from crashing (or silently corrupting) the zero-copy data plane."""

    def __init__(self, msg):
        super().__init__(msg, code=409)


class KvExportNotFound(ServerError):
    """A KV-export descriptor fetch (or attach) named a generation id
    with no live ``kvexport/<gen_id>`` region — never exported, already
    dropped, or TTL-expired with its replay entry — HTTP 404 / gRPC
    NOT_FOUND.  The caller falls back to the fused (re-prefill) path;
    answering a typed 404 here is what keeps a dropped region from
    surfacing later as a crash inside the ``paged_gather`` scatter."""

    def __init__(self, msg):
        super().__init__(msg, code=404)


class KvExportConflict(ServerError):
    """A KV export was claimed twice: the transfer contract is
    one-shot (exactly one decode-role replica re-scatters a prefill
    leg's pages), so a second descriptor fetch for the same generation
    is a typed conflict — HTTP 409 / gRPC ABORTED — not a silent
    double-attach racing the first consumer's drop."""

    def __init__(self, msg):
        super().__init__(msg, code=409)


class UnknownGeneration(ServerError):
    """A stream-resume request named a generation id this replica does
    not hold (never issued, already resumed, or aged out of the replay
    buffer) — HTTP 404 / gRPC NOT_FOUND.  Resume is same-endpoint only:
    generation replay state is replica-local."""

    def __init__(self, msg):
        super().__init__(msg, code=404)
