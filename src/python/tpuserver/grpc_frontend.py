"""gRPC frontend: serves the KServe-v2 GRPCInferenceService (including
decoupled bidirectional streaming and the XLA shared-memory verbs) on a
``grpc.server``, delegating to ``tpuserver.core.InferenceServer``.

The service layer is a generic-handler table over the vendored pb2 messages
(tritonclient/grpc/_service.py) — same wire protocol as the reference's
generated stubs.
"""

import time
from concurrent import futures

import numpy as np

import grpc

from tpuserver.core import (
    InferRequest,
    RequestedOutput,
    ServerError,
    SERVER_EXTENSIONS,
    SERVER_NAME,
    SERVER_VERSION,
)
from tritonclient.grpc import grpc_service_pb2 as pb
from tritonclient.grpc._service import METHODS, SERVICE
from tpuserver.tensor_io import (
    array_from_binary as _array_from_raw,
    binary_from_array as _raw_from_array,
)
from tritonclient.utils import triton_to_np_dtype

_TYPED_FIELDS = {
    "BOOL": "bool_contents",
    "INT8": "int_contents",
    "INT16": "int_contents",
    "INT32": "int_contents",
    "INT64": "int64_contents",
    "UINT8": "uint_contents",
    "UINT16": "uint_contents",
    "UINT32": "uint_contents",
    "UINT64": "uint64_contents",
    "FP32": "fp32_contents",
    "FP64": "fp64_contents",
    "BYTES": "bytes_contents",
}


def _param_value(p):
    field = p.WhichOneof("parameter_choice")
    return getattr(p, field) if field else None


def _params_dict(param_map):
    return {k: _param_value(v) for k, v in param_map.items()}




class _CoreBridge:
    """Protobuf <-> core translation + the RPC method implementations."""

    def __init__(self, core):
        self._core = core

    # -- conversion --------------------------------------------------------

    def _request_from_proto(self, request):
        inputs = {}
        shm_input_regions = []
        raw_cursor = 0  # shm inputs do not consume raw_input_contents slots
        for tensor in request.inputs:
            shape = list(tensor.shape)
            tparams = _params_dict(tensor.parameters)
            if "shared_memory_region" in tparams:
                inputs[tensor.name] = self._core.read_shm_input(
                    tparams["shared_memory_region"],
                    tparams.get("shared_memory_byte_size", 0),
                    tparams.get("shared_memory_offset", 0),
                    tensor.datatype,
                    shape,
                )
                shm_input_regions.append(
                    tparams["shared_memory_region"])
            elif raw_cursor < len(request.raw_input_contents):
                inputs[tensor.name] = _array_from_raw(
                    request.raw_input_contents[raw_cursor], tensor.datatype,
                    shape,
                )
                raw_cursor += 1
            else:
                field = _TYPED_FIELDS.get(tensor.datatype)
                if field is None:
                    raise ServerError(
                        "input '{}' has no data".format(tensor.name)
                    )
                vals = list(getattr(tensor.contents, field))
                if tensor.datatype == "BYTES":
                    arr = np.array(vals, dtype=np.object_).reshape(shape)
                else:
                    arr = np.array(
                        vals, dtype=triton_to_np_dtype(tensor.datatype)
                    ).reshape(shape)
                inputs[tensor.name] = arr
        requested = None
        if request.outputs:
            requested = []
            for out in request.outputs:
                oparams = _params_dict(out.parameters)
                requested.append(
                    RequestedOutput(
                        out.name,
                        binary_data=True,
                        class_count=oparams.get("classification", 0),
                        shm_region=oparams.get("shared_memory_region"),
                        shm_byte_size=oparams.get(
                            "shared_memory_byte_size", 0
                        ),
                        shm_offset=oparams.get("shared_memory_offset", 0),
                    )
                )
        core_request = InferRequest(
            request.model_name,
            request.model_version,
            request.id,
            inputs,
            requested,
            _params_dict(request.parameters),
        )
        # decoupled models pin these for the stream's lifetime (409 on
        # a concurrent unregister of a region backing a live view)
        core_request.shm_input_regions = tuple(shm_input_regions)
        return core_request

    def _response_to_proto(self, resp):
        out = pb.ModelInferResponse(
            model_name=resp.model_name,
            model_version=resp.model_version,
            id=resp.id,
        )
        for key, value in (resp.parameters or {}).items():
            if isinstance(value, bool):
                out.parameters[key].bool_param = value
            elif isinstance(value, int):
                out.parameters[key].int64_param = value
            else:
                out.parameters[key].string_param = str(value)
        for spec, array, delivery in resp.outputs:
            tensor = out.outputs.add()
            tensor.name = spec["name"]
            tensor.datatype = spec["datatype"]
            tensor.shape.extend(int(s) for s in spec["shape"])
            if array is None:  # delivered via shared memory
                tensor.parameters[
                    "shared_memory_region"
                ].string_param = delivery["shm_region"]
                tensor.parameters[
                    "shared_memory_byte_size"
                ].int64_param = delivery["shm_byte_size"]
                if delivery["shm_offset"]:
                    tensor.parameters[
                        "shared_memory_offset"
                    ].int64_param = delivery["shm_offset"]
                out.raw_output_contents.append(b"")
            else:
                out.raw_output_contents.append(
                    _raw_from_array(array, spec["datatype"])
                )
        return out

    # -- unary handlers ----------------------------------------------------

    def ServerLive(self, request, context):
        return pb.ServerLiveResponse(live=True)

    def ServerReady(self, request, context):
        # real core state (starting/draining/watchdog-tripped), not a
        # constant: load balancers must see drain begin before requests
        # start failing
        return pb.ServerReadyResponse(ready=self._core.server_ready())

    def ModelReady(self, request, context):
        return pb.ModelReadyResponse(
            ready=self._core.model_ready(request.name, request.version)
        )

    def ServerMetadata(self, request, context):
        return pb.ServerMetadataResponse(
            name=SERVER_NAME,
            version=SERVER_VERSION,
            extensions=SERVER_EXTENSIONS,
        )

    def ServerMetrics(self, request, context):
        """The Prometheus exposition over gRPC: the SAME snapshot the
        HTTP frontend serves at ``GET /metrics``
        (``core.metrics_text()``), carried in the response's
        ``metrics`` string param — scrapers behind a gRPC-only
        deployment lose nothing."""
        resp = pb.LogSettingsResponse()
        resp.settings["metrics"].string_param = self._core.metrics_text()
        return resp

    def ModelMetadata(self, request, context):
        md = self._core.model_metadata(request.name, request.version)
        resp = pb.ModelMetadataResponse(
            name=md["name"], versions=md["versions"], platform=md["platform"]
        )
        for t in md["inputs"]:
            resp.inputs.add(
                name=t["name"], datatype=t["datatype"], shape=t["shape"]
            )
        for t in md["outputs"]:
            resp.outputs.add(
                name=t["name"], datatype=t["datatype"], shape=t["shape"]
            )
        return resp

    def ModelConfig(self, request, context):
        from google.protobuf import json_format

        cfg = self._core.model_config(request.name, request.version)
        config = json_format.ParseDict(
            cfg, pb.model__config__pb2.ModelConfig(),
            ignore_unknown_fields=True,
        )
        return pb.ModelConfigResponse(config=config)

    def ModelStatistics(self, request, context):
        from google.protobuf import json_format

        stats = self._core.model_statistics(request.name, request.version)
        return json_format.ParseDict(
            stats, pb.ModelStatisticsResponse(), ignore_unknown_fields=True
        )

    def RepositoryIndex(self, request, context):
        resp = pb.RepositoryIndexResponse()
        for entry in self._core.repository_index(ready_only=request.ready):
            resp.models.add(**entry)
        return resp

    def RepositoryModelLoad(self, request, context):
        self._core.load_model(request.model_name)
        return pb.RepositoryModelLoadResponse()

    def RepositoryModelUnload(self, request, context):
        unload_dependents = False
        p = request.parameters.get("unload_dependents")
        if p is not None:
            unload_dependents = bool(_param_value(p))
        self._core.unload_model(request.model_name, unload_dependents)
        return pb.RepositoryModelUnloadResponse()

    # -- shared memory -----------------------------------------------------

    def SystemSharedMemoryStatus(self, request, context):
        resp = pb.SystemSharedMemoryStatusResponse()
        for name, region in self._core.system_shm_status(
            request.name
        ).items():
            resp.regions[name].name = region["name"]
            resp.regions[name].key = region["key"]
            resp.regions[name].offset = region["offset"]
            resp.regions[name].byte_size = region["byte_size"]
        return resp

    def SystemSharedMemoryRegister(self, request, context):
        self._core.register_system_shm(
            request.name, request.key, request.offset, request.byte_size
        )
        return pb.SystemSharedMemoryRegisterResponse()

    def SystemSharedMemoryUnregister(self, request, context):
        self._core.unregister_system_shm(request.name)
        return pb.SystemSharedMemoryUnregisterResponse()

    def CudaSharedMemoryStatus(self, request, context):
        resp = pb.CudaSharedMemoryStatusResponse()
        for name, region in self._core.cuda_shm_status(request.name).items():
            resp.regions[name].name = region["name"]
            resp.regions[name].device_id = region["device_id"]
            resp.regions[name].byte_size = region["byte_size"]
        return resp

    def CudaSharedMemoryRegister(self, request, context):
        self._core.register_cuda_shm(
            request.name, request.raw_handle, request.device_id,
            request.byte_size,
        )
        return pb.CudaSharedMemoryRegisterResponse()

    def CudaSharedMemoryUnregister(self, request, context):
        self._core.unregister_cuda_shm(request.name)
        return pb.CudaSharedMemoryUnregisterResponse()

    def XlaSharedMemoryStatus(self, request, context):
        resp = pb.XlaSharedMemoryStatusResponse()
        for name, region in self._core.xla_shm_status(request.name).items():
            resp.regions[name].name = region["name"]
            resp.regions[name].device_ordinal = region["device_ordinal"]
            resp.regions[name].byte_size = region["byte_size"]
        return resp

    def XlaSharedMemoryRegister(self, request, context):
        self._core.register_xla_shm(
            request.name, request.raw_handle, request.device_ordinal,
            request.byte_size,
        )
        return pb.XlaSharedMemoryRegisterResponse()

    def XlaSharedMemoryUnregister(self, request, context):
        self._core.unregister_xla_shm(request.name)
        return pb.XlaSharedMemoryUnregisterResponse()

    # -- settings ----------------------------------------------------------

    def TraceSetting(self, request, context):
        settings = {}
        for key, val in request.settings.items():
            settings[key] = list(val.value)
        if settings:
            result = self._core.update_trace_settings(
                request.model_name or None, settings
            )
        else:
            result = self._core.get_trace_settings(
                request.model_name or None
            )
        resp = pb.TraceSettingResponse()
        for key, values in result["settings"].items():
            resp.settings[key].value.extend(values)
        return resp

    def LogSettings(self, request, context):
        settings = {}
        for key, val in request.settings.items():
            field = val.WhichOneof("parameter_choice")
            if field is not None:
                settings[key] = getattr(val, field)
        if settings:
            result = self._core.update_log_settings(settings)
        else:
            result = self._core.get_log_settings()
        resp = pb.LogSettingsResponse()
        for key, value in result.items():
            if isinstance(value, bool):
                resp.settings[key].bool_param = value
            elif isinstance(value, int):
                resp.settings[key].uint32_param = value
            else:
                resp.settings[key].string_param = str(value)
        return resp

    # -- inference ---------------------------------------------------------

    @staticmethod
    def _stamp_deadline(core_request, context):
        """Thread the client's gRPC context deadline into the core as a
        monotonic bound (None when the client set none): the scheduler
        expires pending admissions and retires in-flight slots past it,
        and the typed DeadlineExceeded maps back to DEADLINE_EXCEEDED."""
        remaining = context.time_remaining()
        if remaining is not None:
            core_request.deadline = time.monotonic() + remaining
        return core_request

    def ModelInfer(self, request, context):
        core_request = self._stamp_deadline(
            self._request_from_proto(request), context
        )
        resp = self._core.infer(core_request)
        return self._response_to_proto(resp)

    # concurrent in-flight non-decoupled requests per stream: clients
    # pipeline on one bidi stream, and serializing every dispatch would
    # waste the device while a response is in flight
    STREAM_CONCURRENCY = 8

    def ModelStreamInfer(self, request_iterator, context):
        """Bidi stream: each request may yield 0..N responses (decoupled
        models); errors are delivered in-band via error_message so the
        stream survives bad requests (reference server semantics).

        Non-decoupled requests execute concurrently (bounded) and their
        responses interleave in completion order — each response carries
        its request id, matching server stream semantics.  Decoupled
        requests keep strict sequential handling by default: their
        multi-response ordering is part of the model's contract.

        Continuous-batching decoupled models (``concurrent_decoupled``,
        e.g. llama with ``max_slots > 1``) are the exception the core
        reports via ``requires_stream_order``: their stream requests run
        concurrently like unary ones, so several generations submitted
        on ONE bidi stream decode interleaved on the chip — each slot's
        per-step token fans out as a response tagged with its request id
        and the client demultiplexes.  Within one generation, token
        order is still the emission order of its scheduler slot.
        """
        import queue as _queue
        import threading as _threading

        # bounded: restores response backpressure that direct generator
        # yields gave (a slow reader must slow producers, not buffer
        # unboundedly)
        out = _queue.Queue(maxsize=self.STREAM_CONCURRENCY * 4)
        inflight = _threading.Semaphore(self.STREAM_CONCURRENCY)
        pending = [0]
        done_feeding = _threading.Event()
        cancelled = _threading.Event()
        lock = _threading.Lock()
        _SENTINEL = object()

        def emit(item):
            """put with cancellation: a gone client must not wedge
            producer threads on a full queue."""
            while not cancelled.is_set():
                try:
                    out.put(item, timeout=0.25)
                    return True
                except _queue.Full:
                    continue
            return False

        def finish_one():
            with lock:
                pending[0] -= 1
                if pending[0] == 0 and done_feeding.is_set():
                    emit(_SENTINEL)

        def run_one(core_request, bounded=True):
            try:
                for resp in self._core.infer_stream(core_request):
                    if cancelled.is_set() or not context.is_active():
                        break  # stop generating for a gone client
                    if not emit(pb.ModelStreamInferResponse(
                            infer_response=self._response_to_proto(resp))):
                        break
            except ServerError as e:
                emit(pb.ModelStreamInferResponse(error_message=str(e)))
            except Exception as e:
                emit(pb.ModelStreamInferResponse(
                    error_message="unexpected error: {}".format(e)))
            finally:
                if bounded:
                    inflight.release()
                finish_one()

        def feed():
            try:
                for request in request_iterator:
                    if cancelled.is_set():
                        break
                    try:
                        core_request = self._stamp_deadline(
                            self._request_from_proto(request), context)
                    except Exception as e:
                        emit(pb.ModelStreamInferResponse(
                            error_message=str(e)))
                        continue
                    try:
                        ordered = self._core.requires_stream_order(
                            core_request.model_name)
                        unbounded = self._core.is_concurrent_decoupled(
                            core_request.model_name)
                    except Exception:
                        ordered = False
                        unbounded = False
                    if not unbounded:
                        # scheduler-backed generations self-limit via
                        # their slot count; holding a semaphore slot for
                        # a whole generation would cap one client stream
                        # at STREAM_CONCURRENCY regardless of max_slots
                        # AND stall this feed loop behind it
                        inflight.acquire()
                    with lock:
                        pending[0] += 1
                    if ordered:
                        # sequential: decoupled response bursts and
                        # sequence-state step order are contractual
                        run_one(core_request)
                    else:
                        _threading.Thread(
                            target=run_one,
                            args=(core_request, not unbounded),
                            daemon=True,
                        ).start()
            except grpc.RpcError:
                pass  # client cancelled/disconnected: normal stream end
            finally:
                done_feeding.set()
                with lock:
                    if pending[0] == 0:
                        emit(_SENTINEL)

        _threading.Thread(target=feed, daemon=True).start()
        try:
            from tpuserver import faults as _faults

            while True:
                item = out.get()
                if item is _SENTINEL:
                    return
                # chaos hook: kill the bidi stream mid-flight (the
                # raised FaultInjected aborts the RPC with a stream-
                # level error) so client reconnect+resume is drivable
                # end-to-end; skip=N drops after the Nth response
                _faults.fire("grpc.stream_infer", self._core.fault_scope)
                yield item
        finally:
            # reader gone (cancel/deadline/exit): release producers and
            # stop outstanding generation
            cancelled.set()
            while True:
                try:
                    out.get_nowait()
                except _queue.Empty:
                    break


def _wrap_unary(bridge, name):
    method = getattr(bridge, name)

    def handler(request, context):
        try:
            return method(request, context)
        except ServerError as e:
            if getattr(e, "retry_after", None) is not None:
                # the gRPC twin of the HTTP Retry-After header: clients
                # with a retry policy read it from trailing metadata
                context.set_trailing_metadata(
                    (("retry-after", str(int(e.retry_after))),)
                )
            context.abort(_status_code(e.code), str(e))
        except Exception as e:
            context.abort(grpc.StatusCode.INTERNAL, str(e))

    return handler


def _status_code(http_code):
    return {
        400: grpc.StatusCode.INVALID_ARGUMENT,
        404: grpc.StatusCode.NOT_FOUND,
        409: grpc.StatusCode.ABORTED,  # shm region still referenced
        422: grpc.StatusCode.INVALID_ARGUMENT,  # quarantined slot
        429: grpc.StatusCode.RESOURCE_EXHAUSTED,
        500: grpc.StatusCode.INTERNAL,
        501: grpc.StatusCode.UNIMPLEMENTED,
        503: grpc.StatusCode.UNAVAILABLE,
        504: grpc.StatusCode.DEADLINE_EXCEEDED,
    }.get(http_code, grpc.StatusCode.UNKNOWN)


class GrpcFrontend:
    """A grpc.server hosting the full GRPCInferenceService."""

    def __init__(self, core, host="127.0.0.1", port=0, max_workers=32):
        self._core = core
        self._host = host
        self._max_workers = max_workers
        self._requested_port = port
        self._server = None
        self._port = None

    def start(self):
        # kept on the frontend so a fleet-transition test (or an ops
        # hot-swap) can repoint the serving core under a fixed address
        self._bridge = bridge = _CoreBridge(self._core)
        handlers = {}
        for name, (req_cls, resp_cls, kind) in METHODS.items():
            if kind == "unary":
                handlers[name] = grpc.unary_unary_rpc_method_handler(
                    _wrap_unary(bridge, name),
                    request_deserializer=req_cls.FromString,
                    response_serializer=resp_cls.SerializeToString,
                )
            else:
                handlers[name] = grpc.stream_stream_rpc_method_handler(
                    getattr(bridge, name),
                    request_deserializer=req_cls.FromString,
                    response_serializer=resp_cls.SerializeToString,
                )
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=self._max_workers),
            options=[
                ("grpc.max_send_message_length", -1),
                ("grpc.max_receive_message_length", -1),
                # tolerate client-side keepalive pings (role of Triton's
                # --grpc-keepalive-* server flags): no ping strikes, any
                # ping interval accepted even without in-flight data
                ("grpc.http2.max_ping_strikes", 0),
                ("grpc.http2.min_recv_ping_interval_without_data_ms", 10),
                ("grpc.keepalive_permit_without_calls", 1),
            ],
        )
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, handlers),)
        )
        self._port = self._server.add_insecure_port(
            "{}:{}".format(self._host, self._requested_port)
        )
        self._server.start()
        self._core.attach_frontend()
        self._attached = True
        return self

    @property
    def port(self):
        return self._port

    @property
    def url(self):
        return "{}:{}".format(self._host, self._port)

    def stop(self, grace=None):
        if self._server is not None:
            # bounded wait: a handler thread wedged in user/model code
            # (e.g. a compile) cannot be interrupted and must not hang
            # the owner's shutdown forever
            if not self._server.stop(grace).wait(timeout=10):
                import logging

                logging.getLogger(__name__).warning(
                    "grpc frontend did not terminate within 10s "
                    "(a handler thread is still running); the port may "
                    "stay bound"
                )
            self._server = None
            if getattr(self, "_attached", False):
                # only an attach that actually happened may detach: an
                # unpaired detach would close a shared core under
                # another live frontend
                self._attached = False
                self._core.detach_frontend()
