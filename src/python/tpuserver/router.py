"""Fleet router: a server-side front-tier that makes N replicas look
like ONE resilient KServe server.

The resilience stack so far lives either in the replica (deadlines,
shedding, the self-healing scheduler) or in the client
(``tritonclient.EndpointPool``) — so every one of "millions of users"
must run a smart client, and a replica death still strands its
replica-local replay state (stream resume is same-endpoint only).
:class:`FleetRouter` moves that intelligence server-side: a thin HTTP
process speaking the same KServe v2 + ``/generate_stream`` surface as a
replica, load-balancing N backends with four robustness behaviors:

1. **Health/drain-aware routing.**  A background prober polls every
   replica's ``/v2/health/stats`` (the cheap lifecycle + scheduler-
   counter snapshot — no per-model inference statistics) and folds it
   into a per-replica eligibility flag and load score: draining,
   tripped (restart budget exhausted), or stopped replicas rotate out
   *before* a request lands on them, and requests go to the
   least-loaded eligible replica.  Generation admissions additionally
   carry **prefix affinity**: the router hashes the prompt's leading
   tokens and prefers (as a load-score bonus, never an eligibility
   override) the replica that last served that prefix — whose radix
   prefix cache is already warm, so the shared-system-prompt traffic
   shape prefills nearly free fleet-wide.  A router-level
   ``max_inflight`` cap sheds excess load with a typed 429 +
   ``Retry-After`` instead of queueing.
2. **Sticky resume.**  Every routed generation gets a router-assigned
   ``generation_id`` and a generation→home-replica map whose TTL
   matches the replicas' ``replay_ttl_s``; a reconnect carrying
   ``Last-Event-ID`` (or ``resume_generation_id``) replays the
   client-acked gap from the router's own event buffer and routes the
   live continuation home to the replica that owns the replay state.
3. **Cross-replica resume handoff.**  When the home replica is dead or
   tripped, the router re-admits ``prompt + emitted-token history`` on
   a healthy replica (greedy decode is deterministic, so the
   continuation is token-identical — the same invariant the
   scheduler's supervised restart relies on) and splices it behind the
   replayed prefix with continued sequence numbers: a replica loss no
   longer kills in-flight generations, and the client never learns a
   handoff happened.  Handoff needs the ``PROMPT_IDS`` /
   ``MAX_TOKENS`` / ``TOKEN`` generate contract; other streams degrade
   to passthrough (failover before the first token only).
4. **Passthrough resilience.**  Unary requests ride a failover loop:
   connect-phase and typed-overload failures (the
   ``tritonclient._auxiliary.FAILURE_*`` classification) fall through
   to another replica under the request's own deadline budget (its
   ``timeout`` parameter); typed non-overload answers relay untouched
   — every replica would say the same.

A **plain** ``tritonclient.http`` client pointed at the router gets all
of this for free — resume included — with no ``EndpointPool``.  The
router's own surface adds ``/router/stats`` (failover/handoff/shed
counters + per-replica routing state) and ``/router/replicas`` (the
membership admin surface: list / add / remove) for perf tooling, ops,
and the fleet supervisor (``tpuserver.fleet``).

**Dynamic membership.**  The replica set is live state, not a
construction-time constant: :meth:`FleetRouter.add_replica` joins a
replica (its prober spins up, traffic routes to it once a probe sees
it ready) and :meth:`FleetRouter.remove_replica` retires one
mid-flight.  Removal keeps sticky state honest: a removed replica's
homed generations never route to the dead address again — a resume
either hands off (handoff-capable streams re-admit prompt + history on
a live replica) or answers a typed 404.  Every forwarding loop
snapshots the membership once per request, so a concurrent removal can
neither skew an attempt budget nor index into a mutated list.

Run one with ``python tools/router.py --backends a:8000,b:8000``; see
docs/resilience.md "Fleet router" for the full semantics and
``tools/chaos_smoke.py --router`` / ``--fleet`` for the soaks.
"""

import http.client
import json
import os
import random
import re
import socket
import socketserver
import statistics
import sys
import threading
import time
import uuid
import zlib
from collections import OrderedDict

from tpuserver._http_base import (
    BaseHttpHandler, ClientGone as _ClientGone, RelayStream, SseRelayLoop)
from tpuserver.disagg import PhaseSplitOrchestrator
from tpuserver.journal import JournalFollower, JournalWriter, read_journal
from tpuserver.metrics import (
    MetricsRegistry,
    is_cumulative,
    parse_prometheus_text,
    _fmt_value,
    _render_labels,
)
from tritonclient._auxiliary import (
    FAILURE_CONNECT,
    FAILURE_INTERRUPTED,
    RetryPolicy,
)

__all__ = ["FleetRouter"]

_GENERATE_STREAM_URI = re.compile(
    r"^/v2/models/[^/]+(/versions/[^/]+)?/generate_stream$"
)

#: Mutating verbs whose side effect lives on ONE server (shm regions,
#: repository state, settings): the router broadcasts them to every
#: replica — routing them through failover would land the mutation on
#: an arbitrary replica and desync the fleet (same contract as
#: ``EndpointPool``'s broadcast set).
_BROADCAST_URI = re.compile(
    r"^/v2/(repository/models/[^/]+/(load|unload)"
    r"|(system|cuda|xla)sharedmemory(/region/[^/]+)?/(register|unregister)"
    r"|logging|trace/setting)$"
)

#: Request headers forwarded to replicas (lowercased).  Hop-by-hop
#: headers (connection, transfer framing) are the router's own;
#: Content-Encoding is absent because the router decodes once and
#: forwards identity.
_FORWARD_REQUEST_HEADERS = (
    "content-type",
    "inference-header-content-length",
    "accept-encoding",
)

#: Replica response headers relayed to the client, in canonical casing
#: (the raw-socket client reads them case-sensitively).
_RELAY_RESPONSE_HEADERS = {
    "retry-after": "Retry-After",
    "inference-header-content-length": "Inference-Header-Content-Length",
    "content-encoding": "Content-Encoding",
}


def _relay_headers(resp_headers):
    """The upstream response headers a client must see, re-keyed to
    canonical casing."""
    lowered = {k.lower(): v for k, v in resp_headers.items()}
    return {canon: lowered[k]
            for k, canon in _RELAY_RESPONSE_HEADERS.items()
            if k in lowered}


def _coerce_int(value, default=0):
    try:
        return int(value)
    except (TypeError, ValueError):
        return default


def _probe_phase(url, interval_s):
    """Deterministic per-replica phase offset in ``[0, interval_s)``
    for the health prober.

    Probers created together (router start, a supervisor's fleet-wide
    restart or scale-up) would otherwise all fire on the same cadence
    from the same instant — a synchronized probe storm landing on
    just-booted replicas every ``interval_s``.  Hashing the replica url
    spreads the phases across the whole interval, and stays stable
    across router restarts so the spread never collapses."""
    return (zlib.crc32(url.encode("utf-8")) % 4096) / 4096.0 * interval_s


def _snapshot_signals(snap):
    """``(eligible, load)`` routing signals from one replica's
    ``/v2/health/stats`` snapshot.

    ``ready`` already folds in the lifecycle state machine AND the
    model health veto (a tripped scheduler reports unhealthy), so
    eligibility is the server's own truthful readiness; the load score
    is in-flight requests plus every scheduler's live + queued
    generations — what "least-loaded" means for this stack."""
    if not isinstance(snap, dict):
        return False, float("inf")
    eligible = bool(snap.get("ready")) and snap.get("state") == "ready"
    load = _coerce_int(snap.get("inflight"))
    for stats in (snap.get("models") or {}).values():
        if not isinstance(stats, dict):
            continue
        if stats.get("tripped") or stats.get("closed"):
            eligible = False  # belt over the ready veto
        load += _coerce_int(stats.get("live_streams"))
        load += _coerce_int(stats.get("pending"))
    return eligible, float(load)


def _generation_contract(request_json):
    """``(prompt, max_tokens, eos_id)`` when the request follows the
    PROMPT_IDS / MAX_TOKENS generate contract (what cross-replica
    handoff re-prefills), else ``(None, None, None)``."""
    prompt = max_tokens = None
    try:
        for tin in request_json.get("inputs") or []:
            if tin.get("name") == "PROMPT_IDS":
                prompt = [int(v) for v in tin.get("data") or []]
            elif tin.get("name") == "MAX_TOKENS":
                max_tokens = int((tin.get("data") or [0])[0])
    except (TypeError, ValueError):
        return None, None, None
    eos = (request_json.get("parameters") or {}).get("eos_id")
    try:
        eos = int(eos) if eos is not None else None
    except (TypeError, ValueError):
        eos = None
    return prompt, max_tokens, eos


def _token_of(payload):
    """The emitted token an SSE event carries (the handoff re-prefill
    feed), or None when the event has no TOKEN output."""
    for out in payload.get("outputs") or []:
        if out.get("name") == "TOKEN":
            data = out.get("data") or []
            try:
                return int(data[0]) if data else None
            except (TypeError, ValueError):
                return None
    return None


def _request_deadline(body, headers):
    """The request's own monotonic deadline from its ``timeout``
    parameter (microseconds, Triton semantics), or None.  Failover
    attempts must fit inside the caller's single budget — a router
    retrying past it would answer a client that stopped waiting."""
    if not body:
        return None
    try:
        hlen = headers.get("inference-header-content-length")
        blob = body[: int(hlen)] if hlen else body
        t = (json.loads(blob).get("parameters") or {}).get("timeout")
        return time.monotonic() + int(t) / 1e6 if t else None
    except (AttributeError, TypeError, ValueError, UnicodeDecodeError):
        # AttributeError: valid JSON that is not an object (e.g. "[]")
        # — the replica owns the typed 400, not the router
        return None


def _rewrite_timeout(body, headers, remaining_s):
    """Rewrite a relayed request's ``timeout`` parameter (µs, Triton
    semantics) to the REMAINING monotonic deadline budget, returning
    ``(body, headers)``.

    The router's failover/hedge loop can burn most of a request's
    budget before an attempt ever reaches a replica; relaying the
    ORIGINAL timeout would let that attempt occupy a replica slot for
    the full budget again — a doomed request the caller already gave
    up on.  The replica resolves its own deadline from this parameter
    (``InferenceServer._resolve_deadline``), so shrinking it here is
    the fleet-wide form of deadline propagation: the scheduler's
    pending-admission expiry and mid-generation retirement fire at the
    caller's true deadline, not a fresh one.  Bodies using the binary
    extension carry the JSON object in their first
    ``inference-header-content-length`` bytes — the rewrite re-frames
    that prefix and updates the header.  Requests without a timeout
    parameter relay untouched (no budget to propagate)."""
    try:
        hlen = headers.get("inference-header-content-length")
        jlen = int(hlen) if hlen else None
        blob = body[:jlen] if jlen is not None else body
        obj = json.loads(blob)
        params = obj.get("parameters")
        if not isinstance(params, dict) or not params.get("timeout"):
            return body, headers
        # floor of 1µs: a non-positive timeout would be a malformed
        # request, and the deadline-exhausted case answered 504 above
        params["timeout"] = max(1, int(remaining_s * 1e6))
        new_blob = json.dumps(obj).encode("utf-8")
        if jlen is None:
            return new_blob, headers
        headers = dict(headers)
        headers["inference-header-content-length"] = str(len(new_blob))
        return new_blob + body[jlen:], headers
    except (AttributeError, TypeError, ValueError, UnicodeDecodeError):
        # malformed body: the replica owns the typed 400
        return body, headers


#: POST routes hedge-safe by construction — mirrors the client pool's
#: hedgeable set (tritonclient._pool: infer / metadata / health): an
#: infer executes the same computation on any replica, so a duplicate
#: in-flight attempt is waste, never corruption.  Generations/streams
#: never hedge (a duplicate stream emits duplicate tokens) and
#: broadcast mutations never hedge (the broadcast path never reaches
#: forward_unary).
_HEDGE_URI = re.compile(r"^/v2/models/[^/]+(/versions/[^/]+)?/infer$")

#: Digest verbs: the rolling latency rings key on a tiny closed verb
#: set — per-path keys would make every model name a cardinality axis
#: and cross-model latencies incomparable anyway.
def _verb_of(path):
    tail = path.rstrip("/").rsplit("/", 1)[-1]
    if tail == "infer":
        return "infer"
    if "/health/" in path:
        return "health"
    return "meta"


class _LatencyRing:
    """Fixed-size ring of completed-request latencies (seconds): O(1)
    memory, O(size·log size) on the rare percentile read.  NOT itself
    thread-safe — lives under the owning ``_Replica``'s lock."""

    __slots__ = ("_values", "_idx", "_count")

    def __init__(self, size=64):
        self._values = [0.0] * int(size)
        self._idx = 0
        self._count = 0

    def record(self, value):
        self._values[self._idx] = float(value)
        self._idx = (self._idx + 1) % len(self._values)
        if self._count < len(self._values):
            self._count += 1

    @property
    def samples(self):
        return self._count

    def percentile(self, pct):
        """Linear-interpolated percentile of the retained window, or
        None when empty (matches perfanalyzer.metrics.percentile so
        the serving side and the measuring side agree on what 'p90'
        means)."""
        if self._count == 0:
            return None
        ordered = sorted(self._values[:self._count])
        if len(ordered) == 1:
            return ordered[0]
        rank = (pct / 100.0) * (len(ordered) - 1)
        lo = int(rank)
        hi = min(lo + 1, len(ordered) - 1)
        frac = rank - lo
        return ordered[lo] * (1.0 - frac) + ordered[hi] * frac


class _Replica:
    """One routed backend: its address plus the prober-fed routing
    state (eligibility, load score, router-local in-flight count) and
    the rolling per-verb latency digest gray-failure ejection reads."""

    def __init__(self, url, digest_window=64):
        host, sep, port = url.rpartition(":")
        if not sep or not host:
            raise ValueError(
                "replica url must be host:port (got {!r})".format(url))
        self.url = url
        self.host = host
        self.port = int(port)
        # set on remove_replica: the prober loop exits and routing
        # state is latched ineligible (a re-added url gets a FRESH
        # _Replica — no breaker/score carryover by construction)
        self.removed = threading.Event()
        self._lock = threading.Lock()
        # optimistic until the first probe lands, like the pool's
        # endpoints — a router must be able to serve before its first
        # probe cycle completes  # guarded-by: _lock
        self._eligible = True
        self._load = 0.0            # guarded-by: _lock
        self._local_inflight = 0    # guarded-by: _lock
        self._requests = 0          # guarded-by: _lock
        self._failures = 0          # guarded-by: _lock
        self._snapshot = None       # guarded-by: _lock
        self._digest_window = int(digest_window)
        # rolling per-verb latency digest (gray-failure signal):
        # verb -> _LatencyRing of completed-request latencies.  Hedge
        # losers and probe RPCs never record — only traffic the client
        # actually waited on.  # guarded-by: _lock
        self._digest = {}
        # soft-ejected: health-eligible but routed around (except the
        # probe fraction) because its recent latency is a fleet
        # outlier.  Health/drain verdicts always dominate — this flag
        # is only ever consulted among ELIGIBLE replicas.
        # # guarded-by: _lock
        self._ejected = False
        self._ejections = 0         # guarded-by: _lock

    def update_snapshot(self, snap):
        eligible, load = _snapshot_signals(snap)
        with self._lock:
            self._snapshot = snap
            self._eligible = eligible
            self._load = load

    def health(self):
        """The last probe's raw health snapshot (None while
        unreachable) — where phase-aware consumers read role and
        per-model queue depth from."""
        with self._lock:
            return self._snapshot

    def role(self):
        """The replica's advertised disaggregated-serving role
        (``"prefill"`` / ``"decode"``), or None for fused replicas and
        while no snapshot is held — an unreachable replica belongs to
        no phase pool."""
        snap = self.health()
        return snap.get("role") if isinstance(snap, dict) else None

    def mark_unreachable(self):
        """A probe or request could not reach the replica: rotate it
        out until a probe sees it healthy again."""
        with self._lock:
            self._eligible = False
            self._snapshot = None
            self._failures += 1

    def note_typed_failure(self):
        """A typed shed (429/503): the replica answered — count it but
        leave rotation to the prober's readiness signal."""
        with self._lock:
            self._failures += 1

    def retire(self):
        """The replica left the membership: stop its prober and latch
        it ineligible so an in-flight request holding a stale snapshot
        never picks it again."""
        self.removed.set()
        with self._lock:
            self._eligible = False
            self._snapshot = None

    def begin_request(self):
        with self._lock:
            self._requests += 1
            self._local_inflight += 1

    def end_request(self):
        with self._lock:
            self._local_inflight -= 1

    def routable(self):
        """``(eligible, effective_load, soft_ejected)``: the probe's
        load score plus the router's own in-flight count against this
        replica — the between-probes signal that keeps routing
        least-loaded — and the gray-failure ejection flag (meaningful
        only when ``eligible``; health and drain always dominate)."""
        with self._lock:
            return (self._eligible, self._load + self._local_inflight,
                    self._ejected)

    # -- latency digest / gray-failure ejection ----------------------------

    def note_latency(self, verb, seconds):
        """Record one completed request the client actually waited on.
        Hedge losers are excluded by the caller: a loser's latency is
        the hedge's artifact (its connection was abandoned), not the
        replica's service time."""
        with self._lock:
            ring = self._digest.get(verb)
            if ring is None:
                ring = self._digest[verb] = _LatencyRing(
                    self._digest_window)
            ring.record(seconds)

    def digest_snapshot(self):
        """``{verb: (p90, p95, samples)}`` of the rolling digest."""
        with self._lock:
            return {
                verb: (ring.percentile(90), ring.percentile(95),
                       ring.samples)
                for verb, ring in self._digest.items()
                if ring.samples
            }

    def hedge_delay(self, verb, min_samples):
        """The replica's own rolling p95 for ``verb`` — the hedge
        delay seed — or None below ``min_samples`` (an empty digest
        would seed hedges from noise)."""
        with self._lock:
            ring = self._digest.get(verb)
            if ring is None or ring.samples < min_samples:
                return None
            return ring.percentile(95)

    def soft_eject(self):
        """Latch the gray-failure ejection flag and RESET the digest:
        re-admission is judged on what the probe-fraction traffic
        measures from now on, not on the slow window that caused the
        ejection (which would otherwise pin the replica out long after
        it recovered).  Returns False when already ejected."""
        with self._lock:
            if self._ejected:
                return False
            self._ejected = True
            self._ejections += 1
            self._digest = {}
            return True

    def readmit(self):
        """Clear the ejection flag (fresh probe-window samples came in
        under the outlier bar).  Returns False when not ejected."""
        with self._lock:
            if not self._ejected:
                return False
            self._ejected = False
            return True

    def status(self):
        """The one-word routing state ops dashboards key on — it is
        what lets a scrape distinguish a gray incident (soft-ejected)
        from a planned drain from a dead process, which raw
        ineligibility collapses into one bit."""
        with self._lock:
            if self.removed.is_set():
                return "removed"
            if not self._eligible:
                if self._snapshot is None:
                    return "unreachable"
                state = self._snapshot.get("state") \
                    if isinstance(self._snapshot, dict) else None
                return "draining" if state == "draining" else "ineligible"
            return "soft-ejected" if self._ejected else "ok"

    def stats(self):
        status = self.status()
        with self._lock:
            return {
                "url": self.url,
                "eligible": self._eligible,
                "load": self._load + self._local_inflight,
                "requests": self._requests,
                "failures": self._failures,
                "status": status,
                "ejected": self._ejected,
                "ejections": self._ejections,
                "digest": {
                    verb: {"p90_s": ring.percentile(90),
                           "p95_s": ring.percentile(95),
                           "samples": ring.samples}
                    for verb, ring in self._digest.items()
                    if ring.samples
                },
            }


class _Generation:
    """Router-side record of one streamed generation: the original
    request (the handoff re-prefill source), every event relayed so far
    (the resume replay buffer), and the home replica that owns the live
    replay state."""

    def __init__(self, gen_id, path, request_json):
        self.gen_id = gen_id
        self.path = path
        self.request = request_json  # read-only after construction
        prompt, max_tokens, eos_id = _generation_contract(request_json)
        self.prompt = prompt
        self.max_tokens = max_tokens
        self.eos_id = eos_id
        # the request's own monotonic deadline (its ``timeout``
        # parameter, µs): every upstream (re)admission — failover,
        # handoff, resume splice — relays the REMAINING budget, so a
        # slow first home cannot grant its successor a fresh window
        try:
            t = (request_json.get("parameters") or {}).get("timeout")
            self.deadline = (time.monotonic() + int(t) / 1e6
                             if t else None)
        except (AttributeError, TypeError, ValueError):
            # AttributeError: valid-JSON non-dict "parameters" — the
            # replica owns the typed 400, not the router
            self.deadline = None
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # rendered SSE blocks; router seq of _events[i] is _base + i.
        # A LIVE generation always has _base == 0; a generation
        # rebuilt from the journal may hold only the retained tail
        # (_base = count of events that aged out with their segments)
        # # guarded-by: _lock
        self._events = []
        self._base = 0          # guarded-by: _lock
        # rebuilt from the crash journal (recovery / standby tailing):
        # the flag that authorizes fast_forward — a live router's
        # watermark can never truthfully trail its client's
        # # guarded-by: _lock
        self._recovered = False
        # the router's journal writer (None on journal-less routers
        # and on standbys): set by register_generation / promote.
        # Appends are a single lock-free deque.append — the relay hot
        # path acquires no lock beyond the _lock it already holds.
        self.journal = None
        self._journaled_bind = False  # guarded-by: _lock
        # emitted TOKEN ints (None once an event arrives without one:
        # the generation is not handoff-capable)  # guarded-by: _lock
        self._tokens = [] if prompt is not None else None
        # router seq = _offset + backend seq (bumped at each handoff:
        # a re-admitted generation restarts backend numbering at 0)
        self._offset = 0        # guarded-by: _lock
        self._home = None       # guarded-by: _lock
        # the home was REMOVED from the membership (vs never assigned):
        # a resume must never dial it again — hand off or typed-404
        self._home_lost = False  # guarded-by: _lock
        self._completed = False  # guarded-by: _lock
        # one serving connection at a time: a fast reconnect waits for
        # the previous relay to notice its dead client  # guarded-by: _lock
        self._busy = False

    # -- serving-slot ownership -------------------------------------------

    def acquire(self, wait_s=5.0):
        deadline = time.monotonic() + wait_s
        with self._cond:
            while self._busy:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
            self._busy = True
            return True

    def release(self):
        with self._cond:
            self._busy = False
            self._cond.notify_all()

    # -- event recording ---------------------------------------------------

    def record_event(self, backend_seq, payload):
        """Rewrite one upstream event into router numbering and append
        it to the replay buffer.  Returns ``(router_seq, block_bytes)``
        or ``(None, None)`` for an upstream replay duplicate."""
        token = _token_of(payload)
        with self._lock:
            seq = self._offset + int(backend_seq)
            expected = self._base + len(self._events)
            if seq < expected:
                return None, None  # upstream replayed an acked event
            params = payload.setdefault("parameters", {})
            params["generation_id"] = self.gen_id
            params["seq"] = expected
            # post-handoff events mark their id line with the handoff
            # epoch ("gen~offset/seq"): router seqs no longer equal the
            # serving replica's own numbering, and a router holding no
            # offset map for the epoch must see that in the client's
            # Last-Event-ID and fail the resume typed instead of
            # forwarding a misaligned replay point to a replica
            gid = (self.gen_id if not self._offset
                   else "{}~{}".format(self.gen_id, self._offset))
            block = (
                "id: {}/{}\n".format(gid, expected).encode("utf-8")
                + b"data: " + json.dumps(payload).encode("utf-8") + b"\n\n"
            )
            self._events.append(block)
            # a live relay just confirmed the watermark: fast_forward
            # disarms — from here a resume point past the watermark is
            # a client lying, not a crash's lost flush window
            self._recovered = False
            if self._tokens is not None:
                if token is None:
                    self._tokens = None  # not re-prefillable
                else:
                    self._tokens.append(token)
            journal = self.journal
            if journal is not None:
                # enqueue-only durability: one lock-free deque append
                # under the _lock the relay already holds — framing,
                # I/O, and fsync happen on the journal's writer thread
                journal.append({"t": "ev", "gen": self.gen_id,
                                "seq": expected, "id": gid, "p": payload})
            return expected, block

    def mark_unresumable(self):
        """The upstream sent an event without a seq (a non-scheduler
        generation): no replay buffer, no handoff — passthrough only."""
        with self._lock:
            self._tokens = None

    def replay_from(self, from_seq):
        """``(blocks, completed, next_seq, available)`` for a client
        resume.  ``available`` is False when ``from_seq`` predates a
        recovered generation's retained journal tail — the events
        before ``_base`` aged out with their segments and cannot be
        replayed."""
        with self._lock:
            if from_seq < self._base:
                return [], self._completed, \
                    self._base + len(self._events), False
            return (
                list(self._events[from_seq - self._base:]),
                self._completed,
                self._base + len(self._events),
                True,
            )

    def fast_forward(self, to_seq):
        """Advance a RECOVERED generation's watermark to a client's
        resume point that is ahead of the journal's last record: the
        crash lost the final flush window, but the client provably
        received those events (its ``Last-Event-ID`` names them) and
        the home replica still holds them — the upstream resume splice
        continues from the client's own position.  The skipped span is
        unreplayable afterwards (``_base`` jumps) and the token
        history is no longer complete, so handoff capability drops.
        Refused (False) on live generations — a live router's
        watermark can never truthfully trail its client's."""
        with self._lock:
            if not self._recovered or self._completed:
                return False
            if to_seq <= self._base + len(self._events):
                return False
            self._base = to_seq
            self._events = []
            self._tokens = None
            return True

    # -- home / lifecycle --------------------------------------------------

    def set_home(self, url, rebase=False):
        """Point the generation at a (new) owning replica; ``rebase``
        restarts backend seq numbering at the current router seq (a
        handed-off generation is a FRESH admission on its new home)."""
        with self._lock:
            self._home = url
            self._home_lost = False
            if rebase:
                self._offset = self._base + len(self._events)
            journal = self.journal
            if journal is not None:
                if not self._journaled_bind:
                    self._journaled_bind = True
                    journal.append({
                        "t": "bind", "gen": self.gen_id,
                        "path": self.path, "req": self.request,
                        "home": url, "offset": self._offset})
                else:
                    journal.append({
                        "t": "home", "gen": self.gen_id,
                        "home": url, "offset": self._offset})

    def home_removed(self, url):
        """The membership dropped ``url``: if it was this generation's
        home, forget the address (resumes must hand off or fail typed,
        never dial a removed replica)."""
        with self._lock:
            if self._home == url and not self._completed:
                self._home = None
                self._home_lost = True

    def complete(self):
        with self._lock:
            already = self._completed
            self._completed = True
            journal = self.journal
            if journal is not None and not already:
                journal.append({"t": "fin", "gen": self.gen_id})

    def emitted(self):
        with self._lock:
            return self._base + len(self._events)

    def snapshot(self):
        with self._lock:
            return {
                "home": self._home,
                "home_lost": self._home_lost,
                "seq": self._base + len(self._events),
                "offset": self._offset,
                "completed": self._completed,
                "handoff_capable": self._tokens is not None,
                "recovered": self._recovered,
            }

    # -- journal recovery --------------------------------------------------

    @classmethod
    def from_journal(cls, gen_id, path, request_json):
        """Rebuild a generation from its journal ``bind`` record.  The
        original request's deadline is NOT reconstructed — it was
        anchored to a dead process's monotonic clock; the replicas
        still enforce their own resolved deadlines."""
        gen = cls(gen_id, path or "", request_json
                  if isinstance(request_json, dict) else {})
        gen.deadline = None
        with gen._lock:
            gen._recovered = True
            # the bind is already durable; re-journaling it on the
            # first post-recovery set_home would only duplicate it
            gen._journaled_bind = True
        return gen

    def apply_home(self, url, offset):
        """Apply a journal ``bind``/``home`` record: the owning
        replica and the handoff offset at that point."""
        with self._lock:
            self._home = url or None
            self._home_lost = url is None
            self._offset = int(offset or 0)

    def apply_event(self, seq, gid, payload):
        """Apply a journal ``ev`` record, rebuilding the exact SSE
        block the client saw.  A seq gap (older segments rotated out,
        or records lost to a crash's final flush window) keeps only
        the contiguous tail ending at ``seq`` — and drops the token
        history, which is no longer complete enough to hand off."""
        with self._lock:
            watermark = self._base + len(self._events)
            if seq < watermark:
                return
            if seq > watermark:
                self._base = seq
                self._events = []
                self._tokens = None
            block = (
                "id: {}/{}\n".format(gid, seq).encode("utf-8")
                + b"data: " + json.dumps(payload).encode("utf-8")
                + b"\n\n"
            )
            self._events.append(block)
            if self._tokens is not None:
                token = _token_of(payload)
                if token is None:
                    self._tokens = None
                else:
                    self._tokens.append(token)

    # -- upstream request builders ----------------------------------------

    def upstream_request(self, resuming):
        """``(body, headers)`` that (re)establishes the upstream
        stream: the original request with the router's generation id
        injected; when ``resuming``, a ``Last-Event-ID`` in the home
        replica's OWN numbering so it replays exactly the gap the
        router has not buffered (usually nothing) and splices live."""
        with self._lock:
            request = dict(self.request)
            params = dict(request.get("parameters") or {})
            params.pop("resume_generation_id", None)
            params.pop("resume_from_seq", None)
            params["generation_id"] = self.gen_id
            self._propagate_deadline(params)
            request["parameters"] = params
            headers = {"Content-Type": "application/json"}
            if resuming:
                backend_last = (self._base + len(self._events)
                                - self._offset - 1)
                headers["Last-Event-ID"] = "{}/{}".format(
                    self.gen_id, backend_last)
            return json.dumps(request).encode("utf-8"), headers

    def _propagate_deadline(self, params):
        """Rewrite ``timeout`` to the generation's REMAINING budget: a
        failover/handoff admission must arrive at its new replica with
        the caller's true deadline, not the original full window (the
        replica resolves its own deadline from this parameter)."""
        if self.deadline is None:
            return
        remaining = self.deadline - time.monotonic()
        params["timeout"] = max(1, int(remaining * 1e6))

    def handoff_request(self):
        """The re-admission body for a healthy replica: the original
        inputs with ``PROMPT_IDS`` extended by every emitted token and
        ``MAX_TOKENS`` shrunk by the emitted count — greedy decode is
        deterministic, so re-prefilling the full emitted prefix yields
        a token-identical continuation (the supervised-restart
        invariant, applied across replicas).  Returns ``None`` when the
        generation is not handoff-capable, or ``b""`` when every token
        was already emitted and only the terminal marker was lost."""
        with self._lock:
            if (self._tokens is None or self.prompt is None
                    or self.max_tokens is None):
                return None
            emitted = len(self._tokens)
            remaining = self.max_tokens - emitted
            if remaining <= 0 or (
                self.eos_id is not None and emitted
                and self._tokens[-1] == self.eos_id
            ):
                return b""
            request = dict(self.request)
            inputs = []
            for tin in request.get("inputs") or []:
                tin = dict(tin)
                if tin.get("name") == "PROMPT_IDS":
                    data = list(self.prompt) + list(self._tokens)
                    tin["data"] = data
                    tin["shape"] = [len(data)]
                elif tin.get("name") == "MAX_TOKENS":
                    tin["data"] = [remaining]
                inputs.append(tin)
            request["inputs"] = inputs
            params = dict(request.get("parameters") or {})
            params.pop("resume_generation_id", None)
            params.pop("resume_from_seq", None)
            params["generation_id"] = self.gen_id
            self._propagate_deadline(params)
            request["parameters"] = params
            return json.dumps(request).encode("utf-8")


class _FleetMetricsAggregator:
    """Churn-safe fleet aggregation of replica ``/metrics`` scrapes.

    The router's ``GET /metrics`` must present ONE fleet view whose
    monotonic counters never decrease — across replica process
    restarts (a respawned replica's counters reset to zero) and
    membership churn (scale-down removes a replica's exposition
    entirely).  Standard federation math: per ``(replica, sample)``
    last-seen values plus a retained base.

    - A **cumulative** sample (``TYPE counter``/``histogram``, or an
      untyped ``*_total``/``*_count`` compatibility family like
      ``nv_inference_count``) whose new value is LOWER than its last
      seen one is a process restart: the pre-reset total folds into
      the base and counting restarts from the new value.
    - A replica that leaves the membership folds its whole last
      contribution into the base — the fleet view keeps everything it
      ever served.
    - **Gauges** are point-in-time: they sum over the replicas
      reachable in THIS scrape, no retained state.

    All state lives under one small lock held only for dict math —
    the scrapes themselves happen outside (R2: no blocking under a
    lock).
    """

    def __init__(self):
        self._lock = threading.Lock()
        # (url, sample_key) -> last seen value  # guarded-by: _lock
        self._last = {}
        # sample_key -> folded pre-reset/pre-removal total  # guarded-by: _lock
        self._base = {}
        # family -> (kind, help)  # guarded-by: _lock
        self._meta = {}
        # monotonic stamp of the last APPLIED fold: concurrent
        # /metrics handlers scrape outside any lock, so an older
        # scrape landing after a newer one must not fold — its lower
        # values would read as counter resets and permanently inflate
        # the fleet totals  # guarded-by: _lock
        self._last_stamp = float("-inf")

    def render(self, live_urls, scrapes, stamp=None, exclude=()):
        """Fold this round of ``scrapes`` (url -> parsed families of
        the replica's exposition) and render the aggregate lines.
        ``stamp`` is the monotonic instant the scrape round STARTED:
        a round older than the last applied one renders the current
        aggregate without folding (stale values never corrupt the
        reset detection).  ``exclude`` names families the caller's own
        registry already rendered — when replicas are themselves
        routers (routers stack), re-emitting their ``tpu_router_*``
        families would declare the same family twice and invalidate
        the exposition."""
        live = set(live_urls)
        exclude = set(exclude)
        with self._lock:
            fold = stamp is None or stamp >= self._last_stamp
            if fold and stamp is not None:
                self._last_stamp = stamp
            gauges = {}
            if fold:
                for url, key in list(self._last):
                    if url not in live:
                        # membership churn: the departed replica's
                        # totals are history the fleet view must keep
                        self._base[key] = (self._base.get(key, 0.0)
                                           + self._last.pop((url, key)))
            for url, families in scrapes.items():
                for fam_name, fam in families.items():
                    if fam_name in exclude:
                        continue
                    kind = fam["type"]
                    self._meta[fam_name] = (kind, fam["help"])
                    cumulative = is_cumulative(fam_name, kind)
                    for sample_name, labels, value in fam["samples"]:
                        key = (fam_name, sample_name,
                               tuple(sorted(labels.items())))
                        if not cumulative:
                            gauges[key] = gauges.get(key, 0.0) + value
                        elif fold:
                            prev = self._last.get((url, key))
                            if prev is not None and value < prev:
                                # counter reset: a healed process
                                self._base[key] = (
                                    self._base.get(key, 0.0) + prev)
                            self._last[(url, key)] = value
            totals = dict(self._base)
            for (_url, key), value in self._last.items():
                totals[key] = totals.get(key, 0.0) + value
            totals.update(gauges)
            meta = dict(self._meta)
        by_family = {}
        for (fam_name, sample_name, labels), value in totals.items():
            if fam_name in exclude:
                continue  # retained state from before an exclusion
            by_family.setdefault(fam_name, []).append(
                (sample_name, labels, value))

        def sample_order(sample):
            # histogram buckets must leave in ascending numeric ``le``
            # order (OpenMetrics consumers reject lexicographic order:
            # "+Inf" < "0.0001" as strings); every other label sorts
            # lexicographically for a stable exposition
            sample_name, labels, _value = sample
            le = dict(labels).get("le")
            if le is None:
                le_key = float("-inf")
            elif le == "+Inf":
                le_key = float("inf")
            else:
                try:
                    le_key = float(le)
                except ValueError:
                    le_key = float("inf")
            rest = tuple(kv for kv in labels if kv[0] != "le")
            return (sample_name, rest, le_key)

        lines = []
        for fam_name in sorted(by_family):
            kind, help_text = meta.get(fam_name, (None, None))
            if help_text:
                lines.append("# HELP {} {}".format(fam_name, help_text))
            if kind:
                lines.append("# TYPE {} {}".format(fam_name, kind))
            for sample_name, labels, value in sorted(
                    by_family[fam_name], key=sample_order):
                lines.append("{}{} {}".format(
                    sample_name, _render_labels(labels),
                    _fmt_value(value)))
        return "\n".join(lines) + "\n" if lines else ""


class FleetRouter:
    """The router process core: replica set, prober, generation
    registry, counters, and the embedded HTTP front-tier.

    Parameters
    ----------
    backends : list[str]
        ``host:port`` of each replica.
    probe_interval_s / probe_timeout_s
        Health-prober cadence and per-probe timeout.  One synchronous
        probe round runs inside :meth:`start` so routing state is real
        before the first request.
    max_inflight : int or None
        Router-level cap on concurrently forwarded requests; excess
        sheds with a typed 429 + ``Retry-After`` instead of queueing.
    gen_ttl_s / gen_capacity
        Generation-registry bounds: match ``gen_ttl_s`` to the
        replicas' ``replay_ttl_s`` (the windows must agree for sticky
        resume to mean anything).
    read_timeout_s / stream_wait_s
        Upstream socket read timeout, and how long a resume waits for
        a previous relay of the same generation to release it.
    outlier_factor / outlier_min_samples / min_eligible / probe_fraction
        Gray-failure ejection (docs/resilience.md "Tail-latency
        defense"): a replica whose recent per-verb p90 (over at least
        ``outlier_min_samples`` of its own completed requests) exceeds
        ``outlier_factor`` × the fleet median is soft-ejected — routed
        around like drain but fed ``probe_fraction`` of real traffic
        so it re-admits itself on recovery.  Ejection never shrinks
        the healthy set below ``min_eligible``.
    eject_interval_s / digest_window
        Ejection-evaluation throttle and the per-verb latency ring
        size (O(1) memory per replica per verb).
    hedge_delay_s
        Opt-in hedged unary requests (None = off): an idempotent
        attempt still pending after the primary's rolling p95 —
        floored at this value, which alone applies while the digest
        is cold — races a duplicate on the next-ranked different
        replica, first response wins.  Never streams, never
        broadcasts.
    journal
        Directory of the crash-durable generation journal
        (docs/resilience.md "Router HA & state durability").  On
        :meth:`start` the router replays every retained record —
        rebuilding sticky bindings, handoff offsets, watermarks, and
        the relayed-event tail — so marked (``gen~offset/seq``)
        resumes survive a router restart, then journals all new
        resume-critical state off the hot relay path.  None (default)
        keeps the pre-journal behavior.
    standby
        Run as a WARM STANDBY: tail ``journal`` (required) instead of
        writing it, keep the replica membership + prober live, but
        shed all /v2 traffic with a typed 503 until :meth:`promote`
        (or ``POST /router/promote``) turns this router active.
    partition_index / partition_count / peers / partition_epoch
        The horizontal front tier (docs/resilience.md "Horizontal
        router tier"): with ``partition_count > 1`` this router owns
        only the generation ids hashing to ``partition_index``
        (``crc32(bare_id) % count``), journals under its own
        per-partition subdirectory (``<journal>/p<index>`` — the
        single-writer discipline holds per partition), and
        peer-forwards everything else to the owner in ``peers`` (the
        url-by-partition map, rebindable at runtime via ``POST
        /router/partition`` with a newer ``epoch``).  A partitioned
        standby tails EVERY partition's journal and is promoted INTO
        one dead active's partition (``promote(partition=k)``).
        ``partition_count == 1`` (default) is the unpartitioned
        single-active behavior, byte-identical to before.
    relay_mode
        ``"selector"`` hands every established token stream to one
        event-loop thread (:class:`~tpuserver._http_base.SseRelayLoop`)
        so thousands of idle streams do not pin a thread each;
        ``"thread"`` keeps the classic thread-per-stream relay.  None
        picks selector for partitioned routers and thread otherwise.
    """

    def __init__(self, backends, host="127.0.0.1", port=0,
                 probe_interval_s=1.0, probe_timeout_s=2.0,
                 max_inflight=None, gen_ttl_s=60.0, gen_capacity=1024,
                 read_timeout_s=600.0, stream_wait_s=5.0, verbose=False,
                 affinity_bonus=2.0, affinity_prefix_tokens=16,
                 outlier_factor=3.0, outlier_min_samples=16,
                 min_eligible=1, probe_fraction=1.0 / 16,
                 eject_interval_s=0.5, digest_window=64,
                 hedge_delay_s=None, journal=None, standby=False,
                 journal_flush_s=0.02, spawn_nonce=None,
                 partition_index=None, partition_count=1, peers=None,
                 partition_epoch=0, relay_mode=None):
        if not backends:
            raise ValueError("FleetRouter requires at least one backend")
        if relay_mode not in (None, "thread", "selector"):
            raise ValueError(
                "relay_mode must be 'thread' or 'selector' "
                "(got {!r})".format(relay_mode))
        partition_count = max(1, int(partition_count))
        if partition_count > 1:
            if partition_index is None and not standby:
                raise ValueError(
                    "a partitioned ACTIVE router needs its partition: "
                    "pass partition_index with partition_count > 1")
            if (partition_index is not None
                    and not 0 <= int(partition_index) < partition_count):
                raise ValueError(
                    "partition_index {} out of range for {} "
                    "partition(s)".format(partition_index, partition_count))
        # spawn identity nonce (fleet supervisor adoption): echoed in
        # health_snapshot so a restarted supervisor can claim this
        # router process the same way it claims replicas
        self.spawn_nonce = spawn_nonce
        if standby and not journal:
            raise ValueError(
                "a standby router needs the journal to tail: pass "
                "journal=<directory> with standby=True")
        if len(set(backends)) != len(backends):
            raise ValueError(
                "FleetRouter backends must be unique: {}".format(backends))
        self._replicas_lock = threading.Lock()
        # -- tail-latency defense knobs (docs/resilience.md) --------------
        # gray-failure ejection: a replica whose recent per-verb p90
        # exceeds outlier_factor x the fleet median (across at least
        # outlier_min_samples of its own samples) is soft-ejected —
        # routed around like drain, but probed with 1/probe_fraction of
        # real traffic so it re-admits itself on recovery.  Ejection
        # NEVER drops the eligible-and-not-ejected set below
        # min_eligible: the fleet degrades to slow, never unavailable.
        self._outlier_factor = float(outlier_factor)
        self._outlier_min_samples = int(outlier_min_samples)
        self._min_eligible = int(min_eligible)
        self._probe_every = max(1, int(round(1.0 / probe_fraction))) \
            if probe_fraction and probe_fraction > 0 else 0
        self._eject_interval_s = float(eject_interval_s)
        self._digest_window = int(digest_window)
        # hedged unary requests: None = off.  When on, an idempotent
        # unary attempt still pending after the primary replica's own
        # rolling p95 — floored at hedge_delay_s, which alone applies
        # while the digest is cold — gets a second attempt on the
        # next-ranked DIFFERENT replica, first response wins.
        self._hedge_delay_s = (float(hedge_delay_s)
                               if hedge_delay_s is not None else None)
        # live membership: add_replica/remove_replica mutate it while
        # requests are in flight, so every consumer goes through
        # _replicas_snapshot()  # guarded-by: _replicas_lock
        self._replicas = [_Replica(url, digest_window=self._digest_window)
                          for url in backends]
        # the policy is only the failure classifier here (classify /
        # should_failover are stateless); attempt budgets are sized
        # per request from the membership snapshot
        self._policy = RetryPolicy(max_attempts=max(2, len(backends)))
        self._probe_interval_s = float(probe_interval_s)
        self._probe_timeout_s = float(probe_timeout_s)
        self._max_inflight = max_inflight
        self._gen_ttl_s = float(gen_ttl_s)
        self._gen_capacity = int(gen_capacity)
        self._read_timeout_s = float(read_timeout_s)
        self._stream_wait_s = float(stream_wait_s)
        self._verbose = verbose
        self._lock = threading.Lock()
        # generation_id -> (generation, expires_monotonic): the sticky
        # map + replay buffer registry, TTL'd and capacity-bounded like
        # the replicas' own replay buffers  # guarded-by: _lock
        self._gens = OrderedDict()
        self._inflight = 0   # guarded-by: _lock
        self._shed = 0       # guarded-by: _lock
        self._failovers = 0  # guarded-by: _lock
        self._handoffs = 0   # guarded-by: _lock
        self._resumed = 0    # guarded-by: _lock
        # gray-failure ejection events (soft-ejections applied) and
        # hedge outcomes: won = the hedge's response was used, lost =
        # the primary answered after the hedge was already issued,
        # cancelled = the hedge was abandoned still in flight when the
        # primary won  # guarded-by: _lock
        self._ejections = 0
        self._hedges = {"won": 0, "lost": 0, "cancelled": 0}
        # rotation counter steering every probe_every'th pick onto a
        # soft-ejected replica (its real-traffic probe)  # guarded-by: _lock
        self._eject_tick = 0
        # -- horizontal front tier (docs/resilience.md "Horizontal
        # router tier"): stable gen-id partitions across N actives ----------
        self._partition_count = partition_count
        # rebound by promote(partition=k)  # guarded-by: _lock
        self._partition_index = (int(partition_index)
                                 if partition_index is not None else None)
        # url-by-partition owner map + its epoch: higher epochs win
        # (supervisor broadcast after a takeover)  # guarded-by: _lock
        self._partition_map = [str(u) for u in peers] if peers else []
        self._partition_epoch = int(partition_epoch)  # guarded-by: _lock
        self._partition_owned = 0      # guarded-by: _lock
        self._partition_forwarded = 0  # guarded-by: _lock
        self._partition_moved = 0      # guarded-by: _lock
        self._relay_mode = relay_mode or (
            "selector" if partition_count > 1 else "thread")
        # the selector relay loop (created in start() when mode is
        # "selector"; None keeps the thread-per-stream relay)
        self._relay_loop = None
        # -- router HA state (docs/resilience.md "Router HA") -------------
        # partitioned actives journal under their own subdirectory —
        # PR 15's single-writer discipline, per partition
        self._journal_base = journal
        if (journal is not None and partition_count > 1
                and partition_index is not None):
            self._journal_dir = os.path.join(
                journal, "p{}".format(int(partition_index)))
        else:
            self._journal_dir = journal
        self._journal_flush_s = float(journal_flush_s)
        # the journal writer (active routers with a journal only);
        # created in start()/promote(), closed in stop()
        self._journal = None
        self._follower = None
        # partitioned standby: one follower per partition journal
        self._followers = None
        self._tail_thread = None
        self._tail_stop = threading.Event()
        # warm-standby flag: /v2 traffic sheds typed 503 while set;
        # promote() clears it  # guarded-by: _lock
        self._standby = bool(standby)
        # promote() in-flight claim: the takeover signal can arrive
        # from an admin POST and a process signal at once, and the
        # promotion body blocks (thread join, file I/O) so it runs
        # OUTSIDE any lock  # guarded-by: _lock
        self._promoting = False
        # SIGTERM drain latch: stop admitting, let in-flight finish
        # # guarded-by: _lock
        self._draining = False
        # generations rebuilt from the journal (boot recovery + standby
        # tailing) and standby->active promotions  # guarded-by: _lock
        self._recovered = 0
        self._takeovers = 0
        # monotonic stamp of the last ejection evaluation (the
        # throttle check-and-set is one atomic region under _lock —
        # two racing callers cannot both pass)  # guarded-by: _lock
        self._eject_eval_last = float("-inf")
        # prefix-affinity routing (the fleet half of the replicas'
        # radix prefix cache): prompt-prefix hash -> (replica url,
        # expires_monotonic).  A generation admission whose prefix was
        # recently served routes to the replica whose radix cache is
        # already warm — as a LOAD-SCORE BONUS only: health, drain and
        # eligibility always win, and a busier-by-more-than-the-bonus
        # warm replica loses to a colder idle one.
        # ``affinity_bonus <= 0`` disables the signal (hash-blind
        # routing — the perfanalyzer A/B control).
        self._affinity_bonus = float(affinity_bonus)
        self._affinity_prefix_tokens = int(affinity_prefix_tokens)
        # prefix hash -> (url, expires)  # guarded-by: _lock
        self._affinity = OrderedDict()
        self._affinity_routed = 0  # guarded-by: _lock
        self._stop = threading.Event()
        self._httpd = _RouterServer((host, port), _RouterHandler)
        self._httpd.router = self
        self._thread = None
        self._started = False    # guarded-by: _replicas_lock
        self._probers = []       # guarded-by: _replicas_lock
        # optional fleet-supervisor stats hook: folded into /router/
        # stats so perf tooling sees restart/scale counters per window
        self._supervisor_stats = None
        # the router tier's own telemetry (collector over stats() — the
        # counters stay singly accounted) + the fleet aggregator behind
        # GET /metrics (docs/observability.md)
        self.metrics = MetricsRegistry()
        self.metrics.register_collector(self._collect_metrics)
        self._aggregator = _FleetMetricsAggregator()
        # disaggregated prefill/decode admission (tpuserver.disagg):
        # engages only when the prober sees BOTH role pools, so a
        # role-less (or single-replica) fleet rides today's fused
        # path byte-identically
        self.disagg = PhaseSplitOrchestrator(self)

    # -- lifecycle ---------------------------------------------------------

    @property
    def port(self):
        return self._httpd.server_address[1]

    @property
    def url(self):
        return "{}:{}".format(self._httpd.server_address[0], self.port)

    def start(self):
        # crash durability first: a journaled router replays its
        # predecessor's resume-critical state BEFORE the first request
        # can name a generation; a standby starts tailing instead
        if self._journal_dir is not None:
            with self._lock:
                standby = self._standby
            if standby:
                if self._partition_count > 1:
                    # a partitioned standby is the warm copy of EVERY
                    # partition: it tails all N journals and only at
                    # promotion binds to the dead active's partition
                    self._followers = [
                        JournalFollower(os.path.join(
                            self._journal_base, "p{}".format(k)))
                        for k in range(self._partition_count)]
                else:
                    self._follower = JournalFollower(self._journal_dir)
                self._tail_thread = threading.Thread(
                    target=self._tail_loop,
                    name="fleet-router-journal-tail", daemon=True)
                self._tail_thread.start()
            else:
                self._recover_journal()
                self._open_journal_writer()
        if self._relay_mode == "selector":
            self._relay_loop = SseRelayLoop(name="fleet-router-relay")
        # one synchronous probe round before serving: routing decisions
        # start from real replica state, not optimism
        self._probe_round()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.05},
            name="fleet-router-http", daemon=True,
        )
        self._thread.start()
        # one persistent prober thread per replica: a black-holed peer
        # costs its own probe_timeout_s without stalling anyone else's
        # cadence, and no per-round thread churn
        with self._replicas_lock:
            self._started = True
            replicas = list(self._replicas)
        for rep in replicas:
            self._spawn_prober(rep)
        return self

    def stop(self):
        self._stop.set()
        self._tail_stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._tail_thread is not None:
            self._tail_thread.join(timeout=5)
            self._tail_thread = None
        if self._relay_loop is not None:
            self._relay_loop.stop()
        journal = self._journal
        if journal is not None:
            journal.close()
        with self._replicas_lock:
            self._started = False
            probers, self._probers = self._probers, []
        for t in probers:
            t.join(timeout=5)

    # -- crash durability: journal recovery / standby / drain --------------

    def _open_journal_writer(self):
        """Open the append side and attach it to every registered
        generation (recovered ones included): from here on, all
        resume-critical state changes are journaled."""
        self._journal = JournalWriter(
            self._journal_dir,
            rotate_interval_s=self._gen_ttl_s,
            flush_interval_s=self._journal_flush_s)
        with self._lock:
            gens = [gen for gen, _ in self._gens.values()]
        for gen in gens:
            gen.journal = self._journal

    def _recover_journal(self):
        """Boot-time replay: rebuild the sticky registry from every
        retained record.  A torn final record (crash mid-write) was
        already truncated by the reader — recovery is never fatal."""
        records, truncated = read_journal(self._journal_dir)
        for rec in records:
            self._apply_journal_record(rec)
        with self._lock:
            recovered = self._recovered
        if records or truncated:
            self._log(
                "journal: replayed {} record(s), {} generation(s) "
                "recovered{}".format(
                    len(records), recovered,
                    ", {} torn segment tail(s) truncated".format(
                        truncated) if truncated else ""))

    def _tail_loop(self):
        """The standby's warm copy: apply journal records as the
        active router writes them."""
        while not self._tail_stop.is_set():
            followers = (self._followers if self._followers is not None
                         else [self._follower])
            for follower in followers:
                if follower is None:
                    continue
                try:
                    for rec in follower.poll():
                        self._apply_journal_record(rec)
                except Exception as e:  # noqa: BLE001 — a bad record
                    # must not end the tail (the next poll continues
                    # past it)
                    self._log("journal tail error: {}".format(e))
            if self._tail_stop.wait(0.05):
                return

    def _apply_journal_record(self, rec):
        """Fold one journal record into the registry (shared by boot
        recovery and the standby tail)."""
        if not isinstance(rec, dict):
            return
        kind = rec.get("t")
        gid = rec.get("gen")
        if not gid or not isinstance(gid, str):
            return
        if kind == "bind":
            gen = self.lookup_generation(gid)
            if gen is None:
                gen = _Generation.from_journal(
                    gid, rec.get("path"), rec.get("req"))
                if self.register_generation(gen, if_absent=True):
                    with self._lock:
                        self._recovered += 1
                else:
                    gen = self.lookup_generation(gid)
            if gen is not None:
                gen.apply_home(rec.get("home"), rec.get("offset"))
            return
        gen = self.lookup_generation(gid)
        if gen is None:
            return
        if kind == "home":
            gen.apply_home(rec.get("home"), rec.get("offset"))
        elif kind == "ev":
            payload = rec.get("p")
            seq = rec.get("seq")
            if isinstance(payload, dict) and isinstance(seq, int):
                gen.apply_event(seq, rec.get("id") or gid, payload)
        elif kind == "fin":
            gen.complete()
        elif kind == "drop":
            self.drop_generation(gid)

    def promote(self, partition=None, peers=None, epoch=None):
        """Turn a standby active (the takeover signal): final journal
        catch-up, open the append side, start serving.  Returns True
        when a promotion happened (False on an already-active router,
        or while another caller's promotion is in flight).

        On a partitioned tier, ``partition`` names the dead active's
        partition this standby is promoted INTO: the journal re-attach
        is scoped to that partition's directory (single-writer holds
        per partition), tailed state belonging to the surviving
        actives' partitions is shed, and ``peers``/``epoch`` rebind
        the ownership map the supervisor broadcast."""
        with self._lock:
            # one atomic claim: the blocking promotion body (thread
            # join, journal file I/O) must not run under a lock
            if not self._standby or self._promoting:
                return False
            self._promoting = True
        try:
            self._tail_stop.set()
            tail = self._tail_thread
            if tail is not None:
                tail.join(timeout=5)
                self._tail_thread = None
            followers = []
            if self._followers is not None:
                followers = list(self._followers)
                self._followers = None
            elif self._follower is not None:
                followers = [self._follower]
                self._follower = None
            for follower in followers:
                # final catch-up: the dead active's last flushed
                # records land before the first request is admitted
                try:
                    for rec in follower.poll():
                        self._apply_journal_record(rec)
                except Exception as e:  # noqa: BLE001
                    self._log("journal catch-up error: {}".format(e))
            if partition is not None and self._partition_count > 1:
                partition = int(partition)
                self._journal_dir = os.path.join(
                    self._journal_base, "p{}".format(partition))
                with self._lock:
                    self._partition_index = partition
                    if peers is not None:
                        self._partition_map = [str(u) for u in peers]
                    if epoch is not None:
                        self._partition_epoch = max(
                            self._partition_epoch, int(epoch))
                    # shed tailed generations the surviving actives
                    # own: their journals stay theirs (no drop record
                    # is written — that would be a second writer)
                    foreign = [
                        gid for gid in self._gens
                        if self._partition_of(gid) != partition]
                    for gid in foreign:
                        self._gens.pop(gid, None)
            self._open_journal_writer()
            with self._lock:
                self._standby = False
                self._takeovers += 1
        finally:
            with self._lock:
                self._promoting = False
        self._log("standby promoted to active (takeover{})".format(
            "" if partition is None
            else ", partition {}".format(partition)))
        return True

    def begin_drain(self):
        """Stop admitting: /v2 traffic sheds typed 503 from here on;
        in-flight requests and streams run to completion."""
        with self._lock:
            self._draining = True

    def drain(self, timeout_s=10.0):
        """SIGTERM drain: stop admitting, wait for in-flight work to
        finish (streams hand off or complete on their own), then flush
        + fsync the journal so a successor recovers everything this
        process relayed.  Returns True when in-flight reached zero."""
        self.begin_drain()
        deadline = time.monotonic() + timeout_s
        drained = False
        while time.monotonic() < deadline:
            with self._lock:
                inflight = self._inflight
            if inflight <= 0:
                drained = True
                break
            time.sleep(0.05)
        journal = self._journal
        if journal is not None:
            journal.flush()
        return drained

    def rejecting(self):
        """Why /v2 traffic is being shed ("standby" / "draining"), or
        None when serving."""
        with self._lock:
            if self._standby:
                return "standby"
            if self._draining:
                return "draining"
        return None

    def _spawn_prober(self, rep):
        thread = threading.Thread(
            target=self._probe_loop_one, args=(rep,),
            name="fleet-router-prober", daemon=True)
        with self._replicas_lock:
            # prune exited probers (removed replicas) so membership
            # churn — a supervisor healing and scaling for days —
            # cannot grow the list without bound
            self._probers = [t for t in self._probers if t.is_alive()]
            self._probers.append(thread)
        thread.start()

    # -- membership --------------------------------------------------------

    def _replicas_snapshot(self):
        """The membership at one instant.  Every request-scoped loop
        works off ONE snapshot so a concurrent add/remove cannot skew
        its attempt budget or index into a mutated list."""
        with self._replicas_lock:
            return list(self._replicas)

    def add_replica(self, url):
        """Join ``url`` to the live membership.  The replica is probed
        once synchronously (outside the lock — a dead address must not
        stall routing) so it either enters with real state or starts
        rotated-out until its prober sees it ready.  Raises
        ``ValueError`` on a malformed or duplicate url."""
        rep = _Replica(url, digest_window=self._digest_window)  # validates host:port
        snap = self._fetch_snapshot(rep)
        if snap is None:
            rep.mark_unreachable()
        else:
            rep.update_snapshot(snap)
        with self._replicas_lock:
            if any(r.url == url for r in self._replicas):
                raise ValueError(
                    "replica {} is already a member".format(url))
            self._replicas.append(rep)
            started = self._started
        if started:
            self._spawn_prober(rep)
        self._log("membership: added replica {}".format(url))
        return rep.stats()

    def remove_replica(self, url):
        """Retire ``url`` from the live membership.  Its prober exits,
        in-flight snapshots see it latched ineligible, and every
        generation homed on it forgets the address — a later resume
        hands off (handoff-capable) or answers typed-404, never dials
        the removed replica.  Raises ``KeyError`` when ``url`` is not a
        member."""
        with self._replicas_lock:
            for i, rep in enumerate(self._replicas):
                if rep.url == url:
                    del self._replicas[i]
                    break
            else:
                raise KeyError(
                    "replica {} is not a member".format(url))
        rep.retire()
        with self._lock:
            gens = [gen for gen, _ in self._gens.values()]
        for gen in gens:
            gen.home_removed(url)
        self._log("membership: removed replica {}".format(url))
        return rep.stats()

    def membership(self):
        """The admin view of the replica set (``/router/replicas``)."""
        return [rep.stats() for rep in self._replicas_snapshot()]

    def attach_supervisor(self, stats_fn):
        """Register a fleet supervisor's ``stats()`` callable: its
        restart/scale counters ride ``/router/stats`` so the perf
        tooling that already diffs router counters per window sees
        process-level healing too."""
        self._supervisor_stats = stats_fn

    # -- health probing ----------------------------------------------------

    def _probe_round(self):
        """One synchronous probe of every replica (the pre-serving round
        :meth:`start` runs, so routing decisions begin from real state —
        an already-draining replica never sees even the first request)."""
        for rep in self._replicas_snapshot():
            snap = self._fetch_snapshot(rep)
            if snap is None:
                rep.mark_unreachable()
            else:
                rep.update_snapshot(snap)

    def _probe_loop_one(self, rep):
        # phase-staggered cadence: a fleet-wide restart or scale-up
        # creates many probers at the same instant; without per-replica
        # jitter they would synchronize into probe storms against
        # just-booted replicas every interval
        interval = self._probe_interval_s
        rng = random.Random(zlib.crc32(rep.url.encode("utf-8")))
        if self._stop.wait(_probe_phase(rep.url, interval)):
            return
        while not (self._stop.is_set() or rep.removed.is_set()):
            snap = self._fetch_snapshot(rep)
            if self._stop.is_set() or rep.removed.is_set():
                return
            if snap is None:
                rep.mark_unreachable()
            else:
                rep.update_snapshot(snap)
            # the ejection controller rides the probe cadence (itself
            # throttled to eject_interval_s): gray verdicts update even
            # when traffic is too sparse to trigger the request-path
            # evaluation
            self._evaluate_ejections()
            if self._stop.wait(interval * rng.uniform(0.9, 1.1)):
                return

    def _fetch_snapshot(self, rep):
        conn = http.client.HTTPConnection(
            rep.host, rep.port, timeout=self._probe_timeout_s)
        try:
            conn.request("GET", "/v2/health/stats")
            resp = conn.getresponse()
            if resp.status != 200:
                return None
            return json.loads(resp.read())
        except (OSError, ValueError, http.client.HTTPException):
            return None
        finally:
            conn.close()

    # -- routing -----------------------------------------------------------

    def pick_replica(self, exclude=(), replicas=None, prefer=None,
                     healthy_only=False):
        """The least-loaded eligible replica (ties break on backend
        order), or — when nothing is eligible — the least-failed
        ineligible one as a last resort, so a fleet whose probes all
        failed transiently still self-heals instead of hard-failing
        every request.  ``exclude`` holds urls already tried;
        ``replicas`` lets a request-scoped loop pick from its own
        membership snapshot.  A removed replica is never picked, even
        from a stale snapshot.

        ``prefer`` names a url whose load score gets the affinity
        bonus subtracted (its radix prefix cache is presumed warm for
        this request) — a bonus on an ELIGIBLE replica's score only,
        never an eligibility override: a draining, tripped or
        much-busier preferred replica still loses.

        Soft-ejected replicas (gray-failure latency outliers) form a
        middle pool: routed around while healthy capacity exists, but
        every ``probe_every``'th pick lands on one as its real-traffic
        probe (how an ejected replica re-earns a digest and re-admits
        itself), and when NOTHING un-ejected is eligible they serve —
        the fleet degrades to slow, never to unavailable."""
        eligible, probation, fallback = [], [], []
        if replicas is None:
            replicas = self._replicas_snapshot()
        for idx, rep in enumerate(replicas):
            if rep.url in exclude or rep.removed.is_set():
                continue
            ok, load, ejected = rep.routable()
            if ok and prefer is not None and rep.url == prefer:
                load -= self._affinity_bonus
            pool = (probation if ok and ejected
                    else eligible if ok else fallback)
            pool.append((load, idx, rep))
        if healthy_only:
            # the hedge/shadow BACKUP pick: racing a suspected-slow
            # primary against another gray (or worse) replica would
            # defeat the whole point — no healthy candidate means no
            # backup, and the caller waits the primary out
            return min(eligible)[2] if eligible else None
        if eligible and probation and self._probe_every:
            with self._lock:
                self._eject_tick += 1
                probe = self._eject_tick % self._probe_every == 0
            if probe:
                return min(probation)[2]
        for pool in (eligible, probation, fallback):
            if pool:
                return min(pool)[2]
        return None

    def _affinity_key(self, prompt):
        """The routing hash of a generation's prompt prefix, or None
        when the request carries no generate contract (or affinity is
        disabled).  Only the first ``affinity_prefix_tokens`` ids
        hash: sharers of a long system prompt must collide even when
        their suffixes differ, so the span must not exceed the SHARED
        part of the traffic's prompts.  The default (16) matches the
        scheduler's default ``page_size`` — the smallest prefix the
        radix cache can share at all, so any population the replica
        tier could deduplicate also collides here.  (A longer span
        only discriminates better when the shared prefix is known to
        be longer — tune ``--affinity-prefix-tokens`` with the
        workload.)"""
        if not prompt or self._affinity_bonus <= 0:
            return None
        head = prompt[:self._affinity_prefix_tokens]
        return zlib.crc32(
            ",".join(str(int(t)) for t in head).encode("ascii"))

    def pick_for_generation(self, gen, exclude=(), replicas=None):
        """Route one generation admission (or handoff) with prefix
        affinity: siblings of a recently routed prompt prefix land on
        the replica whose radix cache already holds it, so the
        fleet-wide prefix-cache hit rate tracks the per-replica one.
        The chosen replica (affine or not) becomes the prefix's new
        home, so a failover or handoff moves the warm set with it.
        ``replicas`` restricts the candidate set (the disagg
        orchestrator passes the prefill pool — the radix caches the
        affinity map points at live there)."""
        key = self._affinity_key(gen.prompt)
        prefer = None
        if key is not None:
            now = time.monotonic()
            with self._lock:
                entry = self._affinity.get(key)
                if entry is not None and entry[1] > now:
                    prefer = entry[0]
        rep = self.pick_replica(exclude=exclude, replicas=replicas,
                                prefer=prefer)
        if rep is None or key is None:
            return rep
        # the map update is last-writer-wins by design: two racing
        # sibling admissions both record a home and the later one
        # simply re-points the prefix — next siblings converge on it.
        # The counter marks admissions that LANDED on their prefix's
        # warm replica (whether or not the bonus was decisive: ties
        # the affine replica would have won anyway still count).
        hit = prefer is not None and rep.url == prefer
        now = time.monotonic()
        with self._lock:  # tpulint: disable=R7 — benign last-writer-wins
            if hit:
                self._affinity_routed += 1
            self._affinity[key] = (rep.url, now + self._gen_ttl_s)
            self._affinity.move_to_end(key)
            while len(self._affinity) > self._gen_capacity:
                self._affinity.popitem(last=False)
        return rep

    def replica_by_url(self, url):
        for rep in self._replicas_snapshot():
            if rep.url == url:
                return rep
        return None

    def any_routable(self):
        return any(rep.routable()[0] for rep in self._replicas_snapshot())

    # -- gray-failure ejection ---------------------------------------------

    def _evaluate_ejections(self, force=False):
        """Differential latency observation (the gray-failure signal):
        compare every replica's recent per-verb p90 against the fleet
        median and soft-eject the outliers.

        Runs throttled to ``eject_interval_s`` (the check-and-stamp is
        one atomic region under ``_lock``, so racing callers — probers
        and request paths — cannot double-evaluate).  The decision
        itself works off one consistent pass: per-replica digests are
        snapshotted first, verdicts computed from the snapshot, then
        applied through the replicas' own atomic
        ``soft_eject``/``readmit`` latches — a replica whose state
        changed concurrently simply reports False and nothing is
        counted.  Invariants:

        - each replica is judged against the median of the OTHER
          covered replicas (leave-one-out: a median that included the
          candidate would be dragged toward it — on a 2-replica fleet
          median-of-2 is the mean, and a 6x outlier reads as under
          2x); at least one OTHER replica must have
          ``outlier_min_samples`` for the verb, so a lone replica (no
          differential signal) and a uniformly slow fleet (load, not
          gray failure) never eject;
        - health/drain dominate: only currently-ELIGIBLE replicas are
          ever ejected, and ineligible ones keep any ejection flag
          (re-judged once they return);
        - ejections never shrink the eligible-and-unejected set below
          ``min_eligible`` — worst outliers go first, the rest stay
          serving (degrade to slow, not to unavailable);
        - re-admission is judged on POST-ejection samples only
          (``soft_eject`` reset the digest): once the probe-fraction
          traffic accumulates ``outlier_min_samples`` under the bar
          for every verb, the replica returns.
        """
        now = time.monotonic()
        with self._lock:
            if not force and (now - self._eject_eval_last
                              < self._eject_interval_s):
                return
            self._eject_eval_last = now
        rows = []  # (rep, eligible, ejected, {verb: (p90, p95, n)})
        for rep in self._replicas_snapshot():
            if rep.removed.is_set():
                continue
            ok, _load, ejected = rep.routable()
            rows.append((rep, ok, ejected, rep.digest_snapshot()))
        # per-verb p90 coverage over eligible UN-EJECTED replicas: the
        # population each candidate is judged against (a draining/dead
        # replica's digest is history, and an ejected replica's probe
        # samples must not drag the median it is judged against)
        coverage = {}  # verb -> [(rep, p90)]
        for rep, ok, ejected, digest in rows:
            if not ok or ejected:
                continue
            for verb, (p90, _p95, n) in digest.items():
                if n >= self._outlier_min_samples and p90 is not None:
                    coverage.setdefault(verb, []).append((rep, p90))

        def fleet_median(verb, exclude_rep=None):
            """Median p90 of the OTHER covered replicas (leave-one-out:
            a median including the candidate is dragged toward it — on
            a 2-replica fleet median-of-2 is the mean and a 6x outlier
            reads as under 2x), or None without a differential."""
            vals = [p90 for rep, p90 in coverage.get(verb, ())
                    if rep is not exclude_rep]
            return statistics.median(vals) if vals else None

        def worst_ratio(rep, digest):
            """max over verbs of p90 / leave-one-out fleet median (0
            when no verb has both enough own samples and at least one
            OTHER covered replica)."""
            worst = 0.0
            for verb, (p90, _p95, n) in digest.items():
                if n < self._outlier_min_samples:
                    continue
                med = fleet_median(verb, exclude_rep=rep)
                if med:
                    worst = max(worst, p90 / med)
            return worst

        # re-admissions first: they grow the healthy pool the
        # min_eligible floor is measured against
        for rep, ok, ejected, digest in rows:
            if not (ok and ejected):
                continue
            judged = [
                (verb, p90, fleet_median(verb))
                for verb, (p90, _p95, n) in digest.items()
                if n >= self._outlier_min_samples
            ]
            if not judged:
                continue  # probe traffic still accumulating
            if all(med is None or p90 <= self._outlier_factor * med
                   for _verb, p90, med in judged):
                if rep.readmit():
                    self._log("gray: re-admitted {} (recent p90 back "
                              "under the outlier bar)".format(rep.url))
        healthy = sum(1 for rep, ok, _ej, _d in rows
                      if ok and not rep.routable()[2])
        candidates = sorted(
            ((worst_ratio(rep, digest), rep) for rep, ok, ejected,
             digest in rows if ok and not ejected),
            key=lambda pair: -pair[0])
        for ratio, rep in candidates:
            if ratio <= self._outlier_factor:
                break  # sorted: nothing further is an outlier
            if healthy - 1 < self._min_eligible:
                self._log(
                    "gray: ejection of {} deferred — only {} healthy "
                    "replica(s), min_eligible={}".format(
                        rep.url, healthy, self._min_eligible))
                break
            if rep.soft_eject():
                healthy -= 1
                with self._lock:
                    self._ejections += 1
                self._log(
                    "gray: soft-ejected {} (p90 {:.1f}x the fleet "
                    "median)".format(rep.url, ratio))

    # -- router-level admission valve --------------------------------------

    def enter_inflight(self):
        with self._lock:
            if (self._max_inflight is not None
                    and self._inflight >= self._max_inflight):
                self._shed += 1
                shed = True
            else:
                self._inflight += 1
                shed = False
        if shed:
            self._log("shed: in-flight cap {} reached".format(
                self._max_inflight))
        return not shed

    def exit_inflight(self):
        with self._lock:
            self._inflight -= 1

    # -- counters ----------------------------------------------------------

    def _log(self, msg):
        if self._verbose:
            print("[fleet-router] " + msg, file=sys.stderr, flush=True)

    def count_failover(self):
        with self._lock:
            self._failovers += 1
        self._log("failover")

    def count_handoff(self):
        with self._lock:
            self._handoffs += 1
        self._log("handoff")

    def count_resume(self):
        with self._lock:
            self._resumed += 1
        self._log("resume")

    # -- horizontal partitioning (docs/resilience.md "Horizontal router
    # tier"): stable gen-id ownership across N simultaneous actives ---------

    @staticmethod
    def partition_of(gen_id, count):
        """The owning partition of a generation id: CRC32 over the
        BARE id — a ``gen~offset`` handoff-epoch suffix is stripped so
        every epoch of one generation hashes to the same owner."""
        base, tilde, off = gen_id.rpartition("~")
        if tilde and base and off.isdigit():
            gen_id = base
        return zlib.crc32(gen_id.encode("utf-8")) % count

    def _partition_of(self, gen_id):
        return self.partition_of(gen_id, self._partition_count)

    def owns_generation(self, gen_id):
        """``(owned, partition)`` for one generation id.  An
        unpartitioned router owns everything."""
        if self._partition_count <= 1:
            return True, 0
        part = self._partition_of(gen_id)
        with self._lock:
            return part == self._partition_index, part

    def partition_peer(self, part):
        """The owning peer's ``host:port`` for ``part`` per the
        current map (None when the map holds no entry for it)."""
        with self._lock:
            if 0 <= part < len(self._partition_map):
                return self._partition_map[part] or None
        return None

    def mint_generation_id(self):
        """A fresh id that hashes into THIS router's partition, so an
        admission landing here stays here (expected ~count draws; the
        unpartitioned path is the plain uuid mint)."""
        if self._partition_count <= 1:
            return uuid.uuid4().hex
        while True:
            gid = uuid.uuid4().hex
            owned, _ = self.owns_generation(gid)
            if owned:
                return gid

    def partition_view(self):
        """The partition surface of ``/router/stats`` and ``GET
        /router/partition``: index/count/epoch, the url-by-partition
        owner map, and the ownership counters."""
        with self._lock:
            return {
                "index": self._partition_index,
                "count": self._partition_count,
                "epoch": self._partition_epoch,
                "map": list(self._partition_map),
                "owned": self._partition_owned,
                "forwarded": self._partition_forwarded,
                "moved": self._partition_moved,
            }

    def adopt_partition_map(self, new_map, epoch):
        """Adopt a broadcast owner map when its epoch is NEWER (an
        equal epoch is an idempotent re-broadcast; an older one is
        stale and refused — the supervisor bumps the epoch on every
        takeover rebind).  Returns the view after the call."""
        epoch = int(epoch)
        applied = False
        with self._lock:
            if epoch > self._partition_epoch:
                old = self._partition_map
                adopted = [str(u) for u in new_map]
                moved = sum(
                    1 for k in range(len(adopted))
                    if k >= len(old) or old[k] != adopted[k])
                self._partition_map = adopted
                self._partition_epoch = epoch
                self._partition_moved += moved
                applied = True
        if applied:
            self._log("partition map epoch {} adopted".format(epoch))
        return self.partition_view()

    def count_partition_owned(self):
        if self._partition_count <= 1:
            return
        with self._lock:
            self._partition_owned += 1

    def count_partition_forwarded(self):
        with self._lock:
            self._partition_forwarded += 1

    # -- generation registry -----------------------------------------------

    def _sweep_gens_locked(self, now):
        expired = [gid for gid, (_, expires) in self._gens.items()
                   if expires <= now]
        for gid in expired:
            self._gens.pop(gid, None)

    def register_generation(self, gen, if_absent=False):
        """Register ``gen`` in the id registry.  With ``if_absent`` the
        insert is atomic with the existence check: a live or parked
        record with the same id wins and the call returns False (a
        fresh admission must never clobber an existing replay
        buffer)."""
        now = time.monotonic()
        # journaled routers persist every registered generation's
        # resume-critical state (the writer is None on standbys and
        # journal-less routers; recovered generations re-attach on
        # promote via _open_journal_writer)
        gen.journal = self._journal
        with self._lock:
            self._sweep_gens_locked(now)
            if if_absent and gen.gen_id in self._gens:
                return False
            self._gens[gen.gen_id] = (gen, now + self._gen_ttl_s)
            self._gens.move_to_end(gen.gen_id)
            while len(self._gens) > self._gen_capacity:
                self._gens.popitem(last=False)
            return True

    def lookup_generation(self, gen_id):
        """The generation record for a resume, with its TTL refreshed
        (a generation being actively resumed is live state)."""
        now = time.monotonic()
        with self._lock:
            self._sweep_gens_locked(now)
            entry = self._gens.get(gen_id)
            if entry is None:
                return None
            gen, _ = entry
            self._gens[gen_id] = (gen, now + self._gen_ttl_s)
            self._gens.move_to_end(gen_id)
            return gen

    def drop_generation(self, gen_id):
        with self._lock:
            entry = self._gens.pop(gen_id, None)
        journal = self._journal
        if journal is not None and entry is not None:
            journal.append({"t": "drop", "gen": gen_id})

    def generation_snapshot(self, gen_id):
        with self._lock:
            entry = self._gens.get(gen_id)
        return entry[0].snapshot() if entry is not None else None

    # -- observability -----------------------------------------------------

    def stats(self):
        with self._lock:
            out = {
                "inflight": self._inflight,
                "max_inflight": self._max_inflight,
                "shed": self._shed,
                "failovers": self._failovers,
                "handoffs": self._handoffs,
                "resumed_streams": self._resumed,
                "generations": len(self._gens),
                "affinity_routed": self._affinity_routed,
                "affinity_entries": len(self._affinity),
                # tail-latency defense: soft-ejection events and hedge
                # outcomes (the per-replica ejected/status/digest view
                # rides each replica's own stats row below)
                "ejections": self._ejections,
                "hedges": sum(self._hedges.values()),
                "hedges_by_outcome": dict(self._hedges),
                # router HA: journal recovery + warm-standby takeover
                "recovered_generations": self._recovered,
                "takeovers": self._takeovers,
                "standby": self._standby,
                "draining": self._draining,
                # horizontal front tier: this router's partition, the
                # url-by-partition owner map, and the map epoch the
                # clients' resume paths chase moved partitions with
                "partition": {
                    "index": self._partition_index,
                    "count": self._partition_count,
                    "owned": self._partition_owned,
                    "forwarded": self._partition_forwarded,
                    "moved": self._partition_moved,
                },
                "peers": list(self._partition_map),
                "epoch": self._partition_epoch,
            }
        relay = {"mode": self._relay_mode}
        if self._relay_loop is not None:
            relay.update(self._relay_loop.stats())
        out["relay"] = relay
        journal = self._journal
        out["journal"] = journal.stats() if journal is not None else None
        out["disagg"] = self.disagg.stats()
        out["replicas"] = [rep.stats() for rep in self._replicas_snapshot()]
        stats_fn = self._supervisor_stats
        if stats_fn is not None:
            try:
                out["supervisor"] = stats_fn()
            except Exception:  # noqa: BLE001 — observability must not
                # take the routing surface down with a dying supervisor
                out["supervisor"] = None
        return out

    def _collect_metrics(self):
        """Scrape-time collector over :meth:`stats`: the router's
        counters (and an attached fleet supervisor's process-healing
        counters) surface in /metrics without a second account of any
        event."""
        snap = self.stats()
        families = [
            ("tpu_router_failovers_total", [({}, snap["failovers"])]),
            ("tpu_router_handoffs_total", [({}, snap["handoffs"])]),
            ("tpu_router_resumed_streams_total",
             [({}, snap["resumed_streams"])]),
            ("tpu_router_shed_total", [({}, snap["shed"])]),
            ("tpu_router_inflight_requests", [({}, snap["inflight"])]),
            ("tpu_router_generations", [({}, snap["generations"])]),
            ("tpu_router_affinity_routed_total",
             [({}, snap["affinity_routed"])]),
            ("tpu_router_ejections_total", [({}, snap["ejections"])]),
            ("tpu_router_hedges_total",
             [({"outcome": outcome}, count) for outcome, count
              in sorted(snap["hedges_by_outcome"].items())]),
            ("tpu_router_recovered_generations_total",
             [({}, snap["recovered_generations"])]),
            ("tpu_router_takeovers_total", [({}, snap["takeovers"])]),
            ("tpu_router_partition_owned_total",
             [({}, snap["partition"]["owned"])]),
            ("tpu_router_partition_forwarded_total",
             [({}, snap["partition"]["forwarded"])]),
            ("tpu_router_partition_moved_total",
             [({}, snap["partition"]["moved"])]),
            ("tpu_router_partition_epoch", [({}, snap["epoch"])]),
        ]
        journal = snap.get("journal")
        if isinstance(journal, dict):
            families.extend([
                ("tpu_router_journal_records_total",
                 [({}, journal.get("records", 0))]),
                ("tpu_router_journal_bytes_total",
                 [({}, journal.get("bytes", 0))]),
                ("tpu_router_journal_fsyncs_total",
                 [({}, journal.get("fsyncs", 0))]),
            ])
        disagg = snap.get("disagg")
        if isinstance(disagg, dict):
            families.extend([
                ("tpu_disagg_splits_total", [({}, disagg["splits"])]),
                ("tpu_disagg_transfers_total",
                 [({}, disagg["transfers"])]),
                ("tpu_disagg_transfer_bytes_total",
                 [({}, disagg["transfer_bytes"])]),
                ("tpu_disagg_transfer_seconds_total",
                 [({}, disagg["transfer_ms_total"] / 1000.0)]),
                ("tpu_disagg_prefill_queue_seconds_total",
                 [({}, disagg["prefill_queue_ms_total"] / 1000.0)]),
            ])
            fallbacks = disagg.get("fallbacks") or {}
            if fallbacks:
                families.append((
                    "tpu_disagg_fallbacks_total",
                    [({"reason": reason}, count)
                     for reason, count in sorted(fallbacks.items())]))
            depths = disagg.get("phase_queue_depth") or {}
            if depths:
                families.append((
                    "tpu_disagg_phase_queue_depth",
                    [({"phase": phase}, depth)
                     for phase, depth in sorted(depths.items())]))
        eligible, load, state, p90 = [], [], [], []
        for rep in snap["replicas"]:
            labels = {"replica": rep["url"]}
            eligible.append((labels, 1 if rep["eligible"] else 0))
            load.append((labels, rep["load"]))
            # one sample per replica, the current state as the label
            # value: a scrape distinguishes a gray incident
            # (soft-ejected) from a planned drain from a dead process
            # — raw ineligibility collapses all three
            state.append((
                {"replica": rep["url"], "state": rep["status"]}, 1))
            for verb, digest in sorted(rep.get("digest", {}).items()):
                if digest.get("p90_s") is not None:
                    p90.append((
                        {"replica": rep["url"], "verb": verb},
                        digest["p90_s"]))
        if eligible:
            families.append(("tpu_router_replica_eligible", eligible))
            families.append(("tpu_router_replica_load", load))
            families.append(("tpu_router_replica_state", state))
        if p90:
            families.append(("tpu_router_replica_p90_seconds", p90))
        sup = snap.get("supervisor")
        if isinstance(sup, dict):
            families.extend([
                ("tpu_fleet_replica_restarts_total",
                 [({}, sup.get("replica_restarts", 0))]),
                ("tpu_fleet_scale_up_total",
                 [({}, sup.get("scale_up_events", 0))]),
                ("tpu_fleet_scale_down_total",
                 [({}, sup.get("scale_down_events", 0))]),
                ("tpu_fleet_retired_replicas_total",
                 [({}, sup.get("retired_replicas", 0))]),
                ("tpu_fleet_replicas_up", [({}, sup.get("up", 0))]),
            ])
            if "adoptions" in sup:
                # presence-guarded: a supervisor snapshot that
                # predates the crash-durability counters (an external
                # /router/stats shape) must not break the scrape
                families.extend([
                    ("tpu_supervisor_adoptions_total",
                     [({}, sup.get("adoptions", 0))]),
                    ("tpu_supervisor_manifest_records_total",
                     [({}, sup.get("manifest_records", 0))]),
                    ("tpu_supervisor_clean_handovers_total",
                     [({}, sup.get("clean_handovers", 0))]),
                    ("tpu_supervisor_stale_children_reaped_total",
                     [({}, sup.get("stale_children_reaped", 0))]),
                ])
        return families

    def _fetch_metrics(self, rep):
        """One replica's raw ``/metrics`` text, or None when
        unreachable (the aggregator keeps its last contribution)."""
        conn = http.client.HTTPConnection(
            rep.host, rep.port, timeout=self._probe_timeout_s)
        try:
            conn.request("GET", "/metrics")
            resp = conn.getresponse()
            if resp.status != 200:
                return None
            return resp.read().decode("utf-8", errors="replace")
        except (OSError, ValueError, http.client.HTTPException):
            return None
        finally:
            conn.close()

    def metrics_text(self):
        """The router's ``GET /metrics`` exposition: its own tier
        counters followed by the FLEET-AGGREGATED replica families —
        every replica's ``/metrics`` scraped (no locks held across the
        sockets) and folded churn-safe, so a scraper pointed at the
        router sees one monotonic fleet view that survives replica
        restarts, scale events, and retirements."""
        replicas = [rep for rep in self._replicas_snapshot()
                    if not rep.removed.is_set()]
        live_urls = [rep.url for rep in replicas]
        # stamp BEFORE the fetches start: concurrent scrapes fold in
        # start order, so a slower round can never overwrite a newer
        # one's last-seen values (see _FleetMetricsAggregator.render)
        stamp = time.monotonic()
        # fan the fetches out like the prober does: a dead replica
        # costs its own probe_timeout_s, never N of them in sequence
        # (a post-SIGKILL scrape must still answer within one timeout)
        results = {}
        results_lock = threading.Lock()

        def fetch_one(rep):
            text = self._fetch_metrics(rep)
            if text is not None:
                with results_lock:
                    results[rep.url] = parse_prometheus_text(text)

        threads = [
            threading.Thread(target=fetch_one, args=(rep,),
                             name="fleet-router-metrics", daemon=True)
            for rep in replicas
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self._probe_timeout_s + 1.0)
        with results_lock:
            scrapes = dict(results)
        own = self.metrics.render()
        # families this tier already declared must not re-emit from
        # the aggregate: when replicas are themselves routers (routers
        # stack), their tpu_router_*/tpu_fleet_* families would
        # otherwise appear twice and invalidate the exposition
        own_names = {
            line.split(" ", 3)[2] for line in own.splitlines()
            if line.startswith("# TYPE ")
        }
        return own + self._aggregator.render(
            live_urls, scrapes, stamp=stamp, exclude=own_names)

    def health_snapshot(self):
        """The router's own replica-shaped ``/v2/health/stats`` answer,
        so routers stack (a router can front other routers) and pools
        can probe them.  A standby or draining router reports
        not-ready (upstream routers and pools rotate it out) with its
        shedding reason as the lifecycle state — the supervisor still
        reads the 200 answer itself as process liveness."""
        routable = self.any_routable()
        rejecting = self.rejecting()
        snap = self.stats()
        snap.update({
            "state": rejecting or ("ready" if routable else "unavailable"),
            "ready": routable and rejecting is None,
            "router": True,
            "models": {},
        })
        if self.spawn_nonce is not None:
            snap["spawn_nonce"] = self.spawn_nonce
        return snap

    # -- unary forwarding --------------------------------------------------

    @staticmethod
    def _upstream_once(rep, method, path, body, headers, timeout_s):
        conn = http.client.HTTPConnection(
            rep.host, rep.port, timeout=timeout_s)
        try:
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
            return resp.status, dict(resp.headers), resp.read()
        finally:
            conn.close()

    def _attempt_unary(self, rep, method, path, body, headers, timeout_s):
        """One upstream attempt with the router's failure
        classification: ``(response, error, kind, elapsed_s)``."""
        error = kind = None
        response = None
        start = time.monotonic()
        rep.begin_request()
        try:
            response = self._upstream_once(
                rep, method, path, body, headers, timeout_s)
        except (ConnectionRefusedError, socket.gaierror) as e:
            error, kind = e, FAILURE_CONNECT
        except (ConnectionError, socket.timeout, OSError,
                http.client.HTTPException) as e:
            error, kind = e, FAILURE_INTERRUPTED
        finally:
            rep.end_request()
        return response, error, kind, time.monotonic() - start

    def _attempt_hedged(self, primary, replicas, tried, method, path,
                        body, headers, timeout_s, verb, probe=False,
                        deadline=None):
        """The Tail-at-Scale hedge: run the primary attempt, and if it
        is still pending after the hedge delay — the primary replica's
        own rolling p95 for this verb, floored at the configured
        ``hedge_delay_s`` (which alone applies while the digest is
        cold) — issue the same request on the next-ranked DIFFERENT
        replica.  First response
        wins; the loser's connection is abandoned (its thread drains
        on its own) and its latency sample is never recorded — a
        loser's service time is the hedge's artifact, not the
        replica's.

        ``probe=True`` is the gray-failure re-admission path: the
        primary is a soft-ejected replica taking its probe-fraction of
        real traffic, so the backup launches IMMEDIATELY (delay zero —
        the probed replica's suspected slowness must never reach the
        client, or the probe fraction would reappear in fleet p99) and
        the probe attempt's own service time records to the probed
        replica's digest when it completes, win or lose, in the
        background — that sample is exactly what re-admission is
        judged on.  Probe pairs are not counted as hedges.

        Returns ``(rep, response, error, kind, elapsed, recorded)``
        for whichever attempt won (the primary's failure when both
        lost), with the second replica added to ``tried``;
        ``recorded`` tells the caller the winner's digest sample was
        already handled here."""
        import queue as _queue

        results = _queue.Queue()

        def run(rep, tag, done=None):
            out = (tag, rep) + self._attempt_unary(
                rep, method, path, body, headers, timeout_s)
            if probe and tag == "primary":
                _t, _r, resp_, err_, _kind, elapsed_ = out
                if err_ is None and resp_[0] < 500 \
                        and not self._policy.should_failover(
                            self._policy.classify_http_status(resp_[0]),
                            idempotent=True):
                    # the probe's measurement, recorded even as a
                    # hedge loser: this is the traffic the ejected
                    # replica re-earns its digest with.  Typed
                    # overload answers (429/503) are excluded exactly
                    # as on the main recording path — a saturated
                    # replica's fast shed responses must not read as a
                    # recovered service time
                    rep.note_latency(verb, elapsed_)
            if done is not None:
                done.set()
            results.put(out)

        threading.Thread(
            target=run, args=(primary, "primary"),
            name="fleet-router-hedge", daemon=True).start()
        first = None
        if not probe:
            # the primary's rolling p95 seeds the delay, FLOORED at the
            # configured hedge_delay_s (the operator's cap on duplicate
            # traffic), which alone applies while the digest is cold
            delay = primary.hedge_delay(verb, self._outlier_min_samples)
            delay = (self._hedge_delay_s if delay is None
                     else max(delay, self._hedge_delay_s))
            delay = min(delay, timeout_s)
            try:
                first = results.get(timeout=delay)
            except _queue.Empty:
                first = None
        if first is not None:
            # the primary answered inside the hedge delay: no hedge
            _tag, rep, response, error, kind, elapsed = first
            return rep, response, error, kind, elapsed, False
        backup = self.pick_replica(exclude=tried, replicas=replicas,
                                   healthy_only=True)
        if backup is None:
            # nowhere healthy to hedge/shadow to: wait the primary out
            _tag, rep, response, error, kind, elapsed = results.get()
            return rep, response, error, kind, elapsed, probe
        tried.add(backup.url)
        backup_done = threading.Event()
        threading.Thread(
            target=run, args=(backup, "hedge", backup_done),
            name="fleet-router-hedge", daemon=True).start()
        winner = None
        losers = []
        for _ in range(2):
            out = results.get()
            if out[3] is None:  # a response (typed or not) wins
                winner = out
                break
            losers.append(out)
        budget_gone = (deadline is not None
                       and deadline - time.monotonic() <= 0)
        for _ltag, lrep, _lresp, lerr, lkind, _lel in losers:
            if budget_gone and isinstance(lerr, socket.timeout):
                # the loser's socket timeout was the CALLER's own
                # deadline clamp (same no-blame rule as the unary
                # path): an impatient request must not rotate healthy
                # replicas out of the fleet
                continue
            if lkind in (FAILURE_CONNECT, FAILURE_INTERRUPTED):
                # a loser that already failed in transport rotates out
                # like any other unreachable peer
                lrep.mark_unreachable()
        if winner is None:
            # both attempts died in transport: surface the primary's
            # failure to the failover loop (the backup replica was
            # rotated out above and sits in ``tried``)
            for tag, rep, response, error, kind, elapsed in losers:
                if tag == "primary":
                    return rep, response, error, kind, elapsed, False
        tag, rep, response, error, kind, elapsed = winner
        if not probe:
            outcome = ("won" if tag == "hedge"
                       else "lost" if backup_done.is_set() else "cancelled")
            with self._lock:
                self._hedges[outcome] += 1
            self._log("hedge {} (primary {}, hedge {})".format(
                outcome, primary.url, backup.url))
        # a probe-primary win was recorded in its own thread; a backup
        # win records normally in the caller
        return (rep, response, error, kind, elapsed,
                probe and tag == "primary")

    def forward_unary(self, method, path, body, headers, idempotent=False):
        """One logical request with failover: connect-phase and typed-
        overload failures fall through to the next replica under the
        request's own deadline budget; a typed 4xx/5xx outside the
        overload set relays untouched (it would be the same on every
        replica).  A request that was *sent* and then lost its
        connection mid-response may already have executed, so it fails
        over only when the caller marks it ``idempotent`` (GETs) —
        otherwise it surfaces as a typed 502 the client's retry policy
        will not blindly re-execute.  Returns
        ``(status, headers, body)``.

        Two tail-defense behaviors ride the loop (docs/resilience.md
        "Tail-latency defense"): every attempt relays the REMAINING
        deadline budget (the ``timeout`` parameter is rewritten per
        attempt — a slow first attempt shrinks the second's budget),
        and with hedging enabled the FIRST attempt of an idempotent
        request races a delayed duplicate on a different replica."""
        deadline = _request_deadline(body, headers)
        verb = _verb_of(path)
        # the pool's idempotency classification: GETs plus the infer
        # verb (re-executing either elsewhere is waste, never
        # corruption) — the precondition for BOTH duplicate-in-flight
        # shapes below (hedges and shadowed ejection probes)
        hedge_safe = idempotent or (
            method == "POST" and _HEDGE_URI.match(path) is not None)
        hedge_ok = self._hedge_delay_s is not None and hedge_safe
        # ONE membership snapshot per logical request: a concurrent
        # remove_replica must not shrink the attempt budget mid-loop
        # or hand the loop a list whose indices shifted under it
        replicas = self._replicas_snapshot()
        tried = set()
        last_response = None
        first_attempt = True
        for _ in range(max(1, 2 * len(replicas))):
            timeout_s = self._read_timeout_s
            attempt_body, attempt_headers = body, headers
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return (504, {}, json.dumps({
                        "error": "router: request deadline exhausted during "
                                 "failover"}).encode("utf-8"))
                # each attempt gets at most the budget that is left: a
                # replica that accepted the connection and then wedged
                # must not hold the request past its own deadline —
                # and the replica itself must see the SHRUNK budget,
                # so its scheduler never queues work for a request
                # whose caller already gave up (deadline propagation)
                timeout_s = min(timeout_s, remaining)
                attempt_body, attempt_headers = _rewrite_timeout(
                    body, headers, remaining)
            rep = self.pick_replica(exclude=tried, replicas=replicas)
            if rep is None:
                break
            tried.add(rep.url)
            recorded = False
            # a soft-ejected pick IS the re-admission probe: shadow it
            # with an immediate backup when a duplicate is safe (the
            # probed slowness must not reach the client); an unsafe
            # verb probes unshadowed — slow for this one caller, but
            # single-execution
            probe = hedge_safe and rep.routable()[2]
            if probe or (hedge_ok and first_attempt):
                (rep, response, error, kind, elapsed,
                 recorded) = self._attempt_hedged(
                    rep, replicas, tried, method, path, attempt_body,
                    attempt_headers, timeout_s, verb, probe=probe,
                    deadline=deadline)
            else:
                response, error, kind, elapsed = self._attempt_unary(
                    rep, method, path, attempt_body, attempt_headers,
                    timeout_s)
            first_attempt = False
            if error is None:
                kind = self._policy.classify_http_status(response[0])
                if not self._policy.should_failover(kind, idempotent):
                    if response[0] < 500 and not recorded:
                        # the gray-failure digest: completed requests
                        # the client actually waited on (5xx answers
                        # and failover casualties measure the failure
                        # path, not the replica's service time)
                        rep.note_latency(verb, elapsed)
                    if response[0] < 500:
                        self._evaluate_ejections()
                    return response
                # typed overload: the replica did no work — another may
                rep.note_typed_failure()
                last_response = response
                self.count_failover()
                continue
            if (isinstance(error, socket.timeout) and deadline is not None
                    and deadline - time.monotonic() <= 0):
                # the attempt's socket timeout was the CALLER's own
                # deadline clamp, not replica sickness: answer the
                # truthful typed 504 (the replica, which received the
                # shrunk budget, is expiring the request on its own
                # deadline path right now) and do not rotate a healthy
                # replica out for our impatience
                return (504, {}, json.dumps({
                    "error": "router: request deadline exhausted during "
                             "upstream attempt to {}".format(rep.url)
                }).encode("utf-8"))
            # transport failure: rotate the replica out until a probe
            # sees it again; fail over when the classification allows
            rep.mark_unreachable()
            if not self._policy.should_failover(kind, idempotent):
                if kind == FAILURE_INTERRUPTED:
                    # the replica may have executed the request;
                    # re-execution elsewhere is not safe and 429/503
                    # would invite a blind client retry
                    return (502, {}, json.dumps({
                        "error": "router: replica {} dropped the "
                                 "connection mid-request: {}".format(
                                     rep.url, error)
                    }).encode("utf-8"))
                break
            self.count_failover()
        if last_response is not None:
            return last_response  # the fleet-wide typed overload answer
        return (503, {"Retry-After": "1"}, json.dumps({
            "error": "router: no replica available for {} {}".format(
                method, path)}).encode("utf-8"))

    def forward_broadcast(self, method, path, body, headers):
        """Apply a per-server mutation to EVERY replica; the first
        failure is relayed after all were attempted (replicas must
        agree on shm regions / repository state or the next routed
        request lands on one missing the side effect)."""
        first_bad = None
        last_ok = None
        for rep in self._replicas_snapshot():
            if rep.removed.is_set():
                continue
            try:
                response = self._upstream_once(
                    rep, method, path, body, headers, self._read_timeout_s)
            except (ConnectionError, socket.timeout, OSError,
                    http.client.HTTPException) as e:
                rep.mark_unreachable()
                if first_bad is None:
                    first_bad = (503, {}, json.dumps({
                        "error": "router: replica {} unreachable during "
                                 "broadcast: {}".format(rep.url, e)
                    }).encode("utf-8"))
                continue
            if response[0] >= 400:
                if first_bad is None:
                    first_bad = response
            else:
                last_ok = response
        if first_bad is not None:
            return first_bad
        if last_ok is not None:
            return last_ok
        return (503, {}, json.dumps(
            {"error": "router: no replica reachable"}).encode("utf-8"))


class _DetachedRelay:
    """The per-stream adapter between :class:`SseRelayLoop` and the
    router's generation bookkeeping — the event-loop mirror of
    ``_RouterHandler._relay_events``.  ``on_line``/``on_upstream_end``
    run on the relay loop's single thread; everything they touch
    (``record_event``, ``complete``, ``drop_generation``) is its own
    lock-protected state, and the journal append stays enqueue-only.

    On upstream EOF without a terminal event ("died" in the threaded
    relay) the loop closes the client abruptly: the client's
    auto-resume reconnects, and the RESUME path — a short-lived
    handler thread — performs the replay splice / cross-replica
    handoff before detaching again.  ``on_closed`` settles the
    accounting the detaching handler deferred: the replica's in-flight
    slot, the generation's serving slot, and the router's own
    inflight gauge."""

    __slots__ = ("_router", "_gen", "_rep", "_on_first")

    def __init__(self, router, gen, rep, on_first):
        self._router = router
        self._gen = gen
        self._rep = rep
        self._on_first = on_first

    def on_line(self, line):
        if not line.startswith(b"data: "):
            # id lines are rebuilt from the payload's seq
            return ("continue", [])
        try:
            payload = json.loads(line[len(b"data: "):])
        except ValueError:
            return ("continue", [])
        if payload.get("final"):
            self._gen.complete()
            return ("final", [b'data: {"final": true}\n\n'])
        if "error" in payload:
            # a typed in-band failure is terminal fleet-wide
            self._router.drop_generation(self._gen.gen_id)
            return ("error", [b"data: " + json.dumps(
                payload).encode("utf-8") + b"\n\n"])
        if self._on_first is not None:
            self._on_first()
            self._on_first = None
        backend_seq = (payload.get("parameters") or {}).get("seq")
        if backend_seq is None:
            # non-resumable upstream: pure passthrough, no replay
            self._gen.mark_unresumable()
            return ("continue", [b"data: " + json.dumps(
                payload).encode("utf-8") + b"\n\n"])
        seq, block = self._gen.record_event(backend_seq, payload)
        if seq is None:
            return ("continue", [])  # upstream replayed an acked event
        return ("continue", [block])

    def on_upstream_end(self):
        pass  # the client's reconnect drives the handoff, see above

    def on_closed(self, reason):
        self._rep.end_request()
        self._gen.release()
        self._router.exit_inflight()


class _RouterServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True
    # a horizontal tier takes connection bursts (10k-stream benches,
    # whole-partition reconnect storms after a sibling's death): the
    # stock backlog of 5 turns those into dial timeouts
    request_queue_size = 128

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._detached_lock = threading.Lock()
        # requests whose sockets an SseRelayLoop adopted (via a dup):
        # shutdown_request must NOT half-close these — a
        # shutdown(SHUT_WR) on the original socket applies to the
        # dup'd one too and would sever the live detached stream
        self._detached = set()  # guarded-by: _detached_lock

    def detach_request(self, request):
        with self._detached_lock:
            self._detached.add(request)

    def shutdown_request(self, request):
        with self._detached_lock:
            detached = request in self._detached
            self._detached.discard(request)
        if detached:
            # close only this server's fd; the relay loop's dup keeps
            # the connection itself alive
            self.close_request(request)
        else:
            super().shutdown_request(request)


class _RouterHandler(BaseHttpHandler):
    """The router's HTTP surface: same wire shape as the replica
    frontend (tpuserver.http_frontend) — the framing is literally the
    same class, ``tpuserver._http_base.BaseHttpHandler`` — but every
    model-facing route forwards to the fleet instead of executing
    locally.  A dead downstream client surfaces as the base class's
    :class:`~tpuserver._http_base.ClientGone`, which the relay loops
    use to park resume state instead of blaming a healthy replica."""

    server_token = b"tpu-triton-router"

    @property
    def router(self):
        return self.server.router

    def _dispatch(self, method):
        try:
            self._route(method)
        except (BrokenPipeError, ConnectionResetError, _ClientGone):
            raise  # dead client socket: handle() ends the connection
        except Exception as e:  # noqa: BLE001 — the router must answer
            # typed even on internal faults; a raw traceback would tear
            # the keep-alive connection instead
            if self._started:
                raise _ClientGone() from e
            self._send_error_json("router error: {}".format(e), 500)

    def _forward_headers(self):
        fwd = {}
        for key in _FORWARD_REQUEST_HEADERS:
            val = self.headers.get(key)
            if val is not None:
                fwd[key] = val
        return fwd

    # -- routing -----------------------------------------------------------

    def _route(self, method):
        path = self.path.split("?", 1)[0]
        router = self.router
        if path == "/v2/health/live":
            return self._send(200)
        if path == "/v2/health/ready":
            return self._send(
                200 if router.any_routable()
                and router.rejecting() is None else 503)
        if path == "/v2/health/stats":
            return self._send_json(router.health_snapshot())
        if path == "/metrics":
            # the fleet scrape surface: router-tier counters + the
            # churn-safe aggregate of every replica's /metrics —
            # protocol parity with the replica frontend (tpulint R8)
            return self._send(
                200, router.metrics_text().encode("utf-8"),
                content_type="text/plain")
        if path == "/router/stats":
            return self._send_json(router.stats())
        if path == "/router/replicas":
            return self._route_replicas_admin(method)
        if path == "/router/partition":
            return self._route_partition_admin(method)
        if path == "/router/promote":
            # the takeover signal: a standby turns active (final
            # journal catch-up included); idempotent on an active.
            # On a partitioned tier the body names the dead active's
            # partition (+ the rebound owner map and its epoch) the
            # standby is promoted INTO.
            if method != "POST":
                return self._send_error_json(
                    "/router/promote supports POST only", 400)
            try:
                request = json.loads(self._read_body() or b"{}")
            except ValueError:
                request = {}
            kwargs = {}
            if isinstance(request, dict):
                if request.get("partition") is not None:
                    kwargs["partition"] = int(request["partition"])
                if request.get("peers") is not None:
                    kwargs["peers"] = list(request["peers"])
                if request.get("epoch") is not None:
                    kwargs["epoch"] = int(request["epoch"])
            promoted = router.promote(**kwargs)
            return self._send_json({
                "promoted": promoted,
                "standby": router.rejecting() == "standby",
            })
        if not (path == "/v2" or path.startswith("/v2/")):
            return self._send_error_json("unknown endpoint: " + path, 404)
        rejecting = router.rejecting()
        if rejecting is not None:
            # standby: the warm copy sheds until promoted; draining: a
            # SIGTERM'd router stops admitting while in-flight streams
            # finish.  Both are typed transitions the clients' resume
            # retry path rides out against the active/peer router.
            return self._send_error_json(
                "router is {}; retry against the active router".format(
                    rejecting)
                if rejecting == "standby"
                else "router is draining; retry later",
                503, {"Retry-After": 1})
        if not router.enter_inflight():
            # the router-level shed valve: typed, with the backoff
            # contract the clients' retry policies key on
            return self._send_error_json(
                "router is at its in-flight request cap; retry later",
                429, {"Retry-After": 1})
        try:
            if (method == "POST"
                    and _GENERATE_STREAM_URI.match(path) is not None):
                return self._route_generate_stream(path)
            body = self._read_body() if method == "POST" else None
            fwd_headers = self._forward_headers()
            if method == "POST" and _BROADCAST_URI.match(path) is not None:
                response = router.forward_broadcast(
                    method, path, body, fwd_headers)
            else:
                response = router.forward_unary(
                    method, path, body, fwd_headers,
                    idempotent=(method == "GET"))
            status, resp_headers, resp_body = response
            relay = _relay_headers(resp_headers)
            content_type = {
                k.lower(): v for k, v in resp_headers.items()
            }.get("content-type", "application/json")
            return self._send(status, resp_body, relay, content_type)
        finally:
            if not self._detached:
                # a detached stream's inflight slot is released by the
                # relay adapter's on_closed, not this handler thread
                router.exit_inflight()

    # -- membership admin surface ------------------------------------------

    def _route_replicas_admin(self, method):
        """``/router/replicas``: GET lists the live membership; POST
        ``{"action": "add"|"remove", "url": "host:port"}`` mutates it —
        the surface the fleet supervisor (and ops) drives elastic
        scaling and planned replacement through."""
        router = self.router
        if method == "GET":
            return self._send_json({"replicas": router.membership()})
        if method != "POST":
            return self._send_error_json(
                "/router/replicas supports GET and POST only", 400)
        try:
            request = json.loads(self._read_body() or b"{}")
            action = request.get("action")
            url = request.get("url")
        except (ValueError, AttributeError):
            return self._send_error_json(
                "malformed /router/replicas request: JSON object with "
                "'action' and 'url' required", 400)
        if action not in ("add", "remove") or not isinstance(url, str):
            return self._send_error_json(
                "bad membership request: action must be 'add' or "
                "'remove' with a 'url' string", 400)
        try:
            if action == "add":
                router.add_replica(url)
            else:
                router.remove_replica(url)
        except (ValueError, KeyError) as e:
            # KeyError reprs its argument with quotes; unwrap
            msg = e.args[0] if e.args else str(e)
            return self._send_error_json(str(msg), 400)
        return self._send_json({"replicas": router.membership()})

    def _route_partition_admin(self, method):
        """``/router/partition``: GET returns this router's partition
        view (index/count/epoch + the url-by-partition owner map and
        ownership counters); POST ``{"action": "set_map", "map":
        [...], "epoch": N}`` adopts a supervisor-broadcast map when
        the epoch is newer — the rebind that repoints a dead active's
        partition at its promoted standby on every router at once."""
        router = self.router
        if method == "GET":
            return self._send_json(router.partition_view())
        if method != "POST":
            return self._send_error_json(
                "/router/partition supports GET and POST only", 400)
        try:
            request = json.loads(self._read_body() or b"{}")
            action = request.get("action")
            new_map = request.get("map")
            epoch = request.get("epoch")
        except (ValueError, AttributeError):
            return self._send_error_json(
                "malformed /router/partition request: JSON object with "
                "'action', 'map' and 'epoch' required", 400)
        if (action != "set_map" or not isinstance(new_map, list)
                or not isinstance(epoch, int)):
            return self._send_error_json(
                "bad partition request: action must be 'set_map' with "
                "a 'map' list and an integer 'epoch'", 400)
        return self._send_json(router.adopt_partition_map(new_map, epoch))

    # -- streaming: sticky resume + cross-replica handoff ------------------

    def _route_generate_stream(self, path):
        router = self.router
        try:
            request_json = json.loads(self._read_body())
        except ValueError as e:
            return self._send_error_json(
                "malformed generate request: {}".format(e), 400)
        parameters = dict(request_json.get("parameters") or {})
        resume_id = None
        resume_from = 0
        last_id = self.headers.get("last-event-id")
        if last_id:
            gid, sep, seq = last_id.rpartition("/")
            if sep and gid:
                try:
                    resume_from = int(seq) + 1
                    resume_id = gid
                except ValueError:
                    pass
        if resume_id is None and parameters.get("resume_generation_id"):
            resume_id = str(parameters["resume_generation_id"])
            resume_from = _coerce_int(parameters.get("resume_from_seq"), 0)
        if resume_id is not None:
            owned, part = router.owns_generation(resume_id)
            if not owned:
                # a sibling's partition: the thin peer hop — a plain
                # client pointed at ANY active still lands correctly
                return self._forward_to_partition(path, part, request_json)
            router.count_partition_owned()
            gen = router.lookup_generation(resume_id)
            handoff_marked = False
            if gen is None:
                # a "gen~offset" id names a handoff epoch (record_event
                # marks post-handoff events): strip it for the registry
                # lookup — the record lives under the bare id
                base, tilde, off = resume_id.rpartition("~")
                if tilde and base and off.isdigit():
                    handoff_marked = True
                    gen = router.lookup_generation(base)
                    if (gen is not None
                            and int(off) > gen.snapshot()["offset"]):
                        # the client saw a handoff epoch this router's
                        # journal never recorded (records lost past the
                        # crash's final flush window): the offset map
                        # for that epoch is unreconstructable, and a
                        # guessed splice could gap or duplicate — the
                        # honest typed 404 below.  A LOWER epoch is
                        # fine: router seqs stayed continuous across
                        # every handoff the registry does know.
                        gen = None
            if gen is None:
                if handoff_marked:
                    # the generation was handed off across replicas and
                    # this router holds no offset map for that epoch
                    # (restart without a journal / aged out / lost
                    # records): router seqs are unreconstructable, and
                    # a guessed replay point could silently gap or
                    # duplicate tokens — fail typed instead
                    return self._send_error_json(
                        "generation '{}' was handed off across replicas "
                        "and its resume state is gone with the "
                        "router".format(resume_id), 404)
                # router restarted or the entry aged out: a replica may
                # still hold the replay state — seq continuity, not
                # endpoint identity, is the contract
                return self._resume_passthrough(path, resume_id, resume_from)
            router.count_resume()
            return self._serve_resume(gen, resume_from)
        explicit_id = parameters.get("generation_id")
        if explicit_id:
            gen_id = str(explicit_id)
            owned, part = router.owns_generation(gen_id)
            if not owned:
                # the client pinned an id that hashes to a sibling's
                # partition: the same thin hop as a resume
                return self._forward_to_partition(path, part, request_json)
        else:
            # router-minted ids hash home by construction
            gen_id = router.mint_generation_id()
        router.count_partition_owned()
        gen = _Generation(gen_id, path, request_json)
        if not router.register_generation(gen, if_absent=True):
            # the id names a live or parked generation: a fresh
            # admission must never clobber a replay buffer the client
            # could still resume from — with ONE exception.  A
            # predecessor that never relayed an event has no resumable
            # state, and the plain client's own reconnect after a
            # drop-before-first-token blind-re-POSTs the identical
            # admission (it has no Last-Event-ID to resume with): that
            # predecessor is superseded, exactly as the replica
            # scheduler supersedes a reused id's parked record.  The
            # insert is atomic with the existence check: two concurrent
            # admissions with the same explicit id cannot both pass.
            superseded = False
            prior = router.lookup_generation(gen_id)
            if (prior is not None and prior.emitted() == 0
                    and prior.acquire(wait_s=router._stream_wait_s)):
                try:
                    if prior.emitted() == 0:
                        router.drop_generation(gen_id)
                        superseded = router.register_generation(
                            gen, if_absent=True)
                finally:
                    prior.release()
            if not superseded:
                return self._send_error_json(
                    "generation id '{}' is already in use".format(gen_id),
                    400)
        gen.acquire(wait_s=0.0)  # fresh record: never contended
        try:
            return self._run_generation(gen, resuming=False)
        finally:
            if not self._detached:
                # a detached stream's serving slot is released by the
                # relay adapter's on_closed, not this handler thread
                gen.release()

    def _serve_resume(self, gen, from_seq):
        """Sticky resume: replay the client's gap from the router's own
        buffer, then splice the live continuation from the home replica
        (or hand off when the home is gone)."""
        router = self.router
        if not gen.acquire(wait_s=router._stream_wait_s):
            return self._send_error_json(
                "generation '{}' is busy on another connection".format(
                    gen.gen_id), 503, {"Retry-After": 1})
        try:
            blocks, completed, next_seq, available = gen.replay_from(
                from_seq)
            if not available:
                # a recovered generation holds only the retained
                # journal tail; a resume point before it is
                # unreplayable — typed, never a silent gap
                return self._send_error_json(
                    "resume point {} of generation '{}' predates the "
                    "recovered journal tail".format(
                        from_seq, gen.gen_id), 404)
            if from_seq > next_seq:
                # ahead of a RECOVERED watermark: the crash lost the
                # final flush window's records, but the client provably
                # received those events — fast-forward and splice from
                # the client's own position (refused on live
                # generations, where a watermark can never trail)
                if gen.fast_forward(from_seq):
                    blocks, completed, next_seq, _ = gen.replay_from(
                        from_seq)
                else:
                    return self._send_error_json(
                        "resume point {} is beyond generation '{}' ({} "
                        "events relayed)".format(
                            from_seq, gen.gen_id, next_seq), 404)
            snapshot = gen.snapshot()
            if (not completed and snapshot["home_lost"]
                    and not snapshot["handoff_capable"]
                    and gen.emitted() > 0):
                # the home replica was REMOVED from the membership and
                # the stream cannot be reconstructed elsewhere: fail
                # typed before the response starts — a partial replay
                # with no continuation would only masquerade as a live
                # stream (and the dead address is never dialed)
                router.drop_generation(gen.gen_id)
                return self._send_error_json(
                    "generation '{}' was homed on a replica that was "
                    "removed from the fleet and is not handoff-capable"
                    .format(gen.gen_id), 404)
            self._ensure_started()
            for block in blocks:
                self._send_chunk(block)
            if completed:
                self._send_chunk(b'data: {"final": true}\n\n')
                self._end_chunks()
                return
            return self._run_generation(gen, resuming=True)
        finally:
            if not self._detached:
                gen.release()

    def _run_generation(self, gen, resuming):
        """Drive one generation to its terminal event, failing over
        (before the first token) or handing off (after it) when the
        serving replica dies.  Caller holds the generation's busy slot
        and has already replayed any client-acked prefix."""
        router = self.router
        snapshot = gen.snapshot()
        rep = None
        # armed by a phase-split plan: frees the prefill replica's KV
        # export once the decode leg's first token proves the attach
        # consumed it (the replay-TTL sweep is the backstop)
        release_export = None
        if resuming and snapshot["home"] is not None:
            rep = router.replica_by_url(snapshot["home"])
        if resuming and rep is not None:
            body, headers = gen.upstream_request(resuming=True)
        elif resuming and (snapshot["home_lost"]
                           or snapshot["home"] is not None):
            # the home replica LEFT THE MEMBERSHIP (remove_replica
            # latched home_lost, or it vanished between the snapshot
            # and the lookup): the dead address is never dialed again.
            # A handoff-capable stream re-admits its emitted history on
            # a live replica; anything else answers typed-404 — unless
            # nothing was ever delivered, where re-routing the original
            # admission cannot duplicate tokens.
            handoff_body = gen.handoff_request()
            if handoff_body is None:
                if gen.emitted() == 0 and not self._started:
                    rep = router.pick_for_generation(gen)
                    body, headers = gen.upstream_request(resuming=False)
                    if rep is not None:
                        gen.set_home(rep.url)
                    resuming = False
                else:
                    return self._stream_fail(
                        gen,
                        "generation '{}' was homed on a replica that was "
                        "removed from the fleet and is not "
                        "handoff-capable".format(gen.gen_id), status=404)
            elif handoff_body == b"":
                # every token already reached the client; only the
                # terminal marker went down with the removed replica
                gen.complete()
                self._ensure_started()
                self._send_chunk(b'data: {"final": true}\n\n')
                self._end_chunks()
                return
            else:
                rep = router.pick_for_generation(gen)
                if rep is None:
                    return self._stream_fail(
                        gen, "no replica available to hand off generation "
                             "'{}'".format(gen.gen_id))
                router.count_handoff()
                gen.set_home(rep.url, rebase=True)
                body = handoff_body
                headers = {"Content-Type": "application/json"}
                resuming = False
        else:
            # fresh admission: phase-split it when both role pools are
            # routable (tpuserver.disagg) — the prefill leg's token has
            # already relayed by the time a plan comes back, and the
            # decode leg below is handoff-shaped, so every later
            # failure heals on the existing machinery
            plan = router.disagg.try_admit(self, gen)
            if plan is not None:
                terminal = plan.get("terminal")
                if terminal == "complete":
                    gen.complete()
                    self._ensure_started()
                    self._send_chunk(b'data: {"final": true}\n\n')
                    self._end_chunks()
                    return
                if terminal == "error":
                    # typed in-band failure already relayed: terminal
                    router.drop_generation(gen.gen_id)
                    self._end_chunks()
                    return
                if terminal == "fail":
                    return self._stream_fail(
                        gen, "generation '{}' lost its prefill leg and "
                             "is not handoff-capable".format(gen.gen_id))
                rep = plan["rep"]
                body, headers = plan["body"], plan["headers"]
                release_export = plan.get("release")
                if rep is None:
                    # the decode pool emptied AFTER the prefill leg
                    # relayed token 0 (composed kills: decode replica
                    # down while the prefill replica streams, then the
                    # fallback picks find nothing).  The stream is
                    # started and ``body`` is already the handoff
                    # re-admission, so failing here is user-visible —
                    # wait out the supervisor heal exactly like the
                    # mid-stream handoff path (found by
                    # tools/chaos_campaign.py --proof seed 10, pinned
                    # in tests/test_chaos_campaign.py)
                    rep = self._wait_for_handoff_replica(gen, None)
                    if rep is not None:
                        gen.set_home(rep.url, rebase=True)
            else:
                # prefix affinity steers siblings of a warm prompt
                # prefix to the replica already holding it
                rep = router.pick_for_generation(gen)
                body, headers = gen.upstream_request(resuming=False)
                if rep is not None:
                    gen.set_home(rep.url)
        attempts = 0
        max_attempts = 2 * len(router._replicas_snapshot()) + 2
        give_up_at = None  # armed mid-stream: wall-clock, not attempts
        while True:
            attempts += 1
            exhausted = attempts > max_attempts
            if exhausted and self._started:
                # mid-stream the attempt cap converts to a wall-clock
                # budget: composed kills (prefill AND decode replica
                # SIGKILLed inside one chaos cycle) can burn the whole
                # cap on pick → dial → die rounds while the fleet is
                # at zero capacity, and an in-band failure here is
                # TERMINAL at the client — user-visible.  The fleet
                # contract is that the supervisor heals within
                # seconds; ride the heal out (found by
                # tools/chaos_campaign.py --proof seed 10, pinned in
                # tests/test_chaos_campaign.py).
                if give_up_at is None:
                    give_up_at = time.monotonic() + self.HANDOFF_WAIT_S
                if time.monotonic() < give_up_at:
                    exhausted = False
                    time.sleep(0.05)
            if rep is None or exhausted:
                return self._stream_fail(
                    gen, "no replica available for generation '{}'".format(
                        gen.gen_id))
            outcome = None
            status_error = None
            conn = None
            rep.begin_request()
            admitted_at = time.monotonic()
            serving_rep = rep
            ttft_fresh = not resuming and gen.emitted() == 0
            try:
                conn = http.client.HTTPConnection(
                    rep.host, rep.port, timeout=router._read_timeout_s)
                conn.request("POST", gen.path, body=body, headers=headers)
                resp = conn.getresponse()
                if resp.status != 200:
                    status_error = (
                        resp.status, dict(resp.headers), resp.read())
                else:
                    # the stream tier's gray-failure sample is TTFT
                    # (total stream time scales with max_tokens, so it
                    # cannot be compared across replicas) — fresh
                    # admissions only: a resume/handoff splice starts
                    # mid-generation and would read artificially fast
                    def _note_ttft():
                        serving_rep.note_latency(
                            "generate_stream",
                            time.monotonic() - admitted_at)

                    on_first = (_note_ttft if ttft_fresh
                                else release_export)
                    release_export = None  # one-shot
                    if router._relay_loop is not None:
                        outcome = self._detach_relay(
                            gen, rep, conn, resp, on_first)
                    if outcome is None:
                        outcome = self._relay_events(gen, resp, on_first)
            except (ConnectionError, socket.timeout, OSError,
                    http.client.HTTPException):
                outcome = "died"
            finally:
                if outcome != "detached":
                    rep.end_request()
                    if conn is not None:
                        conn.close()
            if outcome == "detached":
                # the selector relay owns the stream (and the deferred
                # generation/replica/inflight accounting) from here
                return
            if outcome == "final":
                gen.complete()
                self._ensure_started()
                self._send_chunk(b'data: {"final": true}\n\n')
                self._end_chunks()
                return
            if outcome == "error":
                # a typed in-band failure already relayed: terminal —
                # the generation is dead fleet-wide, drop its state
                router.drop_generation(gen.gen_id)
                self._end_chunks()
                return
            if status_error is not None:
                status, resp_headers, resp_body = status_error
                kind = router._policy.classify_http_status(status)
                failover_ok = (
                    router._policy.should_failover(kind, idempotent=True)
                    or (resuming and status == 404)
                )
                if not failover_ok:
                    # a typed non-overload answer every replica would
                    # repeat: relay it
                    if self._started:
                        try:
                            msg = json.loads(resp_body).get(
                                "error", "upstream failure")
                        except (ValueError, AttributeError):
                            msg = "upstream failure (status {})".format(
                                status)
                        self._send_chunk(b"data: " + json.dumps(
                            {"error": msg}).encode("utf-8") + b"\n\n")
                        self._end_chunks()
                        return
                    return self._send(
                        status, resp_body, _relay_headers(resp_headers))
                rep.note_typed_failure()
            else:
                # transport death mid-request: rotate the replica out
                rep.mark_unreachable()
            if gen.emitted() == 0 and not self._started and not resuming:
                # nothing delivered anywhere yet: a plain failover —
                # re-sending the same admission cannot duplicate tokens.
                # ``_started`` matters independently of the buffer: an
                # unresumable upstream (no seqs) relays events WITHOUT
                # recording them, and re-sending after any of those
                # reached the client would duplicate its tokens
                router.count_failover()
                rep = router.pick_for_generation(gen, exclude={rep.url})
                if rep is not None:
                    gen.set_home(rep.url)
                body, headers = gen.upstream_request(resuming=False)
                continue
            # tokens are out: only a token-identical re-admission keeps
            # the stream gap- and duplicate-free
            handoff_body = gen.handoff_request()
            if handoff_body is None:
                return self._stream_fail(
                    gen,
                    "replica {} lost mid-generation and generation '{}' "
                    "is not handoff-capable".format(rep.url, gen.gen_id))
            if handoff_body == b"":
                # every token was already relayed; only the terminal
                # marker was lost with the replica
                gen.complete()
                self._ensure_started()
                self._send_chunk(b'data: {"final": true}\n\n')
                self._end_chunks()
                return
            new_rep = (router.pick_for_generation(gen, exclude={rep.url})
                       or router.pick_for_generation(gen))
            if new_rep is None:
                # mid-stream zero-capacity window: every routable
                # replica is down at once (composed kills can land
                # between supervisor heals — prefill AND decode
                # SIGKILLed in one chaos cycle).  Tokens are already
                # out, so a typed failure here is USER-VISIBLE and the
                # in-band error event is terminal at the client; the
                # fleet contract is that the supervisor heals the pool
                # within seconds — wait for capacity instead of
                # failing the stream (found by tools/chaos_campaign.py
                # --proof seed 10, pinned in
                # tests/test_chaos_campaign.py)
                new_rep = self._wait_for_handoff_replica(gen, rep.url)
            if new_rep is None:
                return self._stream_fail(
                    gen, "no replica available to hand off generation "
                         "'{}'".format(gen.gen_id))
            router.count_handoff()
            gen.set_home(new_rep.url, rebase=True)
            rep = new_rep
            body = handoff_body
            headers = {"Content-Type": "application/json"}
            resuming = False

    def _relay_events(self, gen, resp, on_first=None):
        """Relay one upstream SSE response: record + rewrite each event
        into router numbering and emit it.  Returns ``"final"``,
        ``"error"`` (typed in-band failure, already relayed), or
        ``"died"`` (EOF without a terminal event — the handoff
        trigger).  Upstream socket failures propagate to the caller's
        transport handler; a dead client raises :class:`_ClientGone`.
        ``on_first`` fires once before the first data event relays —
        the TTFT probe feeding the serving replica's latency digest."""
        for raw in resp:
            line = raw.rstrip(b"\r\n")
            if not line.startswith(b"data: "):
                continue  # id lines are rebuilt from the payload's seq
            payload = json.loads(line[len(b"data: "):])
            if payload.get("final"):
                return "final"
            if "error" in payload:
                self._ensure_started()
                self._send_chunk(b"data: " + json.dumps(payload).encode("utf-8")
                           + b"\n\n")
                return "error"
            # TTFT samples only real token events: an in-band error
            # answer measures the failure path, not service time (a
            # fast-erroring replica must not read as a fast replica —
            # the same exclusion the unary recording path applies)
            if on_first is not None:
                on_first()
                on_first = None
            backend_seq = (payload.get("parameters") or {}).get("seq")
            if backend_seq is None:
                # a non-resumable upstream (no scheduler ids): pure
                # passthrough, no replay buffer, no handoff
                gen.mark_unresumable()
                self._ensure_started()
                self._send_chunk(b"data: " + json.dumps(payload).encode("utf-8")
                           + b"\n\n")
                continue
            seq, block = gen.record_event(backend_seq, payload)
            if seq is None:
                continue  # upstream replayed an event the client acked
            self._ensure_started()
            self._send_chunk(block)
        return "died"

    def _forward_to_partition(self, path, part, request_json):
        """The thin peer hop: a generate-stream request that hashes to
        a sibling's partition relays raw through THIS router to its
        owner, so a plain client pointed at ANY active lands
        correctly.  An unreachable or unmapped owner (the takeover
        window) answers a typed 503 carrying the partition and map
        epoch — the client's reconnect rotation retries until the
        supervisor's rebind lands the promoted standby in the map."""
        router = self.router
        peer = router.partition_peer(part)
        epoch = router.partition_view()["epoch"]
        if peer is None:
            return self._send_json(
                {"error": "partition {} has no live owner yet; "
                          "retry".format(part),
                 "partition": part, "owner": None, "epoch": epoch},
                503, {"Retry-After": 1})
        router.count_partition_forwarded()
        host, _, port = peer.rpartition(":")
        body = json.dumps(request_json).encode("utf-8")
        headers = self._forward_headers()
        headers["Content-Type"] = "application/json"
        last_id = self.headers.get("last-event-id")
        if last_id:
            headers["Last-Event-ID"] = last_id
        conn = None
        try:
            conn = http.client.HTTPConnection(
                host, int(port), timeout=router._read_timeout_s)
            conn.request("POST", path, body=body, headers=headers)
            resp = conn.getresponse()
            if resp.status != 200:
                # the owner's typed answer (404 resume-gone, 503
                # takeover shed, ...) IS the fleet's answer: relay it
                return self._send(
                    resp.status, resp.read(),
                    _relay_headers(dict(resp.headers)))
            for raw in resp:
                line = raw.rstrip(b"\r\n")
                if not (line.startswith(b"id: ")
                        or line.startswith(b"data: ")):
                    continue
                self._ensure_started()
                self._send_chunk(line + b"\n\n"
                                 if line.startswith(b"data: ")
                                 else line + b"\n")
            if self._started:
                self._end_chunks()
            else:
                self._send_error_json(
                    "partition {} owner {} produced no events".format(
                        part, peer), 502)
        except (ConnectionError, socket.timeout, OSError,
                http.client.HTTPException):
            if self._started:
                raise _ClientGone()  # mid-hop loss: the client retries
            return self._send_json(
                {"error": "partition {} owner {} is unreachable; "
                          "retry".format(part, peer),
                 "partition": part, "owner": peer, "epoch": epoch},
                503, {"Retry-After": 1})
        finally:
            if conn is not None:
                conn.close()

    def _detach_relay(self, gen, rep, conn, resp, on_first):
        """Hand an established upstream stream to the router's
        selector relay loop: the handler thread returns once the
        response headers are on the wire, and one event-loop thread
        multiplexes the token relay for every detached stream (the
        thread-per-connection ceiling, retired).  Returns
        ``"detached"``, or None to fall back to the threaded relay."""
        router = self.router
        loop = router._relay_loop
        if loop is None:
            return None
        upstream = getattr(conn, "sock", None)
        dup = None
        drain = upstream
        if upstream is None:
            # read-until-close framing (no Content-Length, not
            # chunked): http.client hands the connection to the
            # response (``will_close``) and drops ``conn.sock`` inside
            # getresponse().  The fd is still open — the response's
            # makefile holds the last io-ref — so adopt a dup: the
            # relay loop owns an independent socket object and the
            # response's eventual GC close cannot sever the stream.
            raw = getattr(getattr(resp, "fp", None), "raw", None)
            base = getattr(raw, "_sock", None)
            if base is None:
                return None
            try:
                upstream = socket.socket(
                    base.family, base.type, base.proto,
                    fileno=os.dup(base.fileno()))
            except OSError:
                return None
            dup = upstream
            drain = base
        # pull the body bytes http.client buffered past the response
        # headers — they belong to the relay stream, not the (about to
        # be neutralized) HTTPResponse.  The non-blocking flip must
        # land on the socket OBJECT the response reads through
        # (``drain``): Python-level timeouts live on the object, not
        # the fd, so flipping only a dup would leave ``read1`` parked
        # in its 10-minute read timeout until the upstream closes.
        saved_timeout = drain.gettimeout()
        drain.setblocking(False)
        leftover = []
        try:
            while True:
                piece = resp.fp.read1(65536)
                if not piece:
                    break
                leftover.append(piece)
        except (BlockingIOError, InterruptedError):
            pass
        except (ValueError, OSError):
            drain.settimeout(saved_timeout)
            if dup is not None:
                try:
                    dup.close()
                except OSError:
                    pass
            return None
        self._ensure_started()
        client = self._detach_socket()
        stream = RelayStream(
            upstream, client, _DetachedRelay(router, gen, rep, on_first),
            leftover=b"".join(leftover),
            chunked_in=bool(getattr(resp, "chunked", True)),
            chunked_out=self._chunked_ok)
        # neutralize http.client's ownership of the detached fd:
        # conn.close()/garbage collection must not close a live socket
        conn.sock = None
        resp.fp = None
        self.server.detach_request(self.connection)
        try:
            loop.adopt(stream)
        except RuntimeError:
            # loop already stopped (router shutdown): the stream dies
            # with this handler — restore the deferred accounting path
            self._detached = False
            for sock in (upstream, client):
                try:
                    sock.close()
                except OSError:
                    pass
            raise _ClientGone()
        return "detached"

    def _resume_passthrough(self, path, resume_id, resume_from):
        """Resume of a generation the router does not hold: one of the
        replicas may still own the replay state (router restart), so
        try each in turn — a 404 from one replica is not the fleet's
        answer.  Relayed raw: without buffered history the router can
        neither rewrite seqs nor hand off."""
        router = self.router
        body = self._read_body()
        headers = self._forward_headers()
        headers["Last-Event-ID"] = "{}/{}".format(resume_id, resume_from - 1)
        replicas = router._replicas_snapshot()
        tried = set()
        last_status = None
        for _ in range(len(replicas)):
            rep = router.pick_replica(exclude=tried, replicas=replicas)
            if rep is None:
                break
            tried.add(rep.url)
            conn = None
            rep.begin_request()
            try:
                conn = http.client.HTTPConnection(
                    rep.host, rep.port, timeout=router._read_timeout_s)
                conn.request("POST", path, body=body, headers=headers)
                resp = conn.getresponse()
                if resp.status == 404:
                    last_status = (resp.status, dict(resp.headers),
                                   resp.read())
                    continue  # another replica may hold the state
                if resp.status != 200:
                    return self._send(
                        resp.status, resp.read(),
                        _relay_headers(dict(resp.headers)))
                router.count_resume()
                for raw in resp:
                    line = raw.rstrip(b"\r\n")
                    if line.startswith(b"id: ") or line.startswith(
                            b"data: "):
                        self._ensure_started()
                        self._send_chunk(line + b"\n\n" if line.startswith(
                            b"data: ") else line + b"\n")
                # a clean upstream end carries its own final event; a
                # mid-stream death simply ends the chunked body with no
                # terminal event, and the resuming client retries
                if self._started:
                    self._end_chunks()
                else:
                    self._send_error_json(
                        "generation '{}' produced no events on "
                        "resume".format(resume_id), 502)
                return
            except (ConnectionError, socket.timeout, OSError,
                    http.client.HTTPException):
                rep.mark_unreachable()
                if self._started:
                    raise _ClientGone()  # mid-relay loss: client retries
                continue
            finally:
                rep.end_request()
                if conn is not None:
                    conn.close()
        if last_status is not None:
            status, resp_headers, resp_body = last_status
            return self._send(status, resp_body)
        return self._send_error_json(
            "unknown generation '{}' and no replica holds it".format(
                resume_id), 404)

    #: mid-stream zero-capacity wait: how long a handoff with tokens
    #: already relayed polls for a healed replica before surfacing the
    #: terminal in-band error.  The supervisor's SIGKILL → respawn →
    #: probe re-admission cycle is seconds; this covers two serial
    #: heals (the composed-kill worst case) with margin.
    HANDOFF_WAIT_S = 15.0

    def _wait_for_handoff_replica(self, gen, dead_url):
        """Poll for a routable handoff target while the supervisor
        heals a zero-capacity fleet; None only after HANDOFF_WAIT_S."""
        router = self.router
        deadline = time.monotonic() + self.HANDOFF_WAIT_S
        while time.monotonic() < deadline:
            time.sleep(0.05)
            rep = (router.pick_for_generation(gen, exclude={dead_url})
                   or router.pick_for_generation(gen))
            if rep is not None:
                return rep
        return None

    def _stream_fail(self, gen, message, status=503):
        """Terminal router-side stream failure: typed (503 by default,
        404 for unresumable-after-removal) before the stream started,
        in-band error event after."""
        self.router.drop_generation(gen.gen_id)
        if self._started:
            self._send_chunk(b"data: " + json.dumps(
                {"error": message}).encode("utf-8") + b"\n\n")
            self._end_chunks()
            return
        headers = {"Retry-After": 1} if status == 503 else None
        self._send_error_json(message, status, headers)
