"""Shared stdlib HTTP request framing for the replica frontend and the
fleet router.

Both ``tpuserver.http_frontend._Handler`` and
``tpuserver.router._RouterHandler`` speak the same hand-rolled
HTTP/1.1 dialect: request-line + header parsing with byte splits (the
stdlib ``BaseHTTPRequestHandler`` parses headers through the email
package at ~300us/request), one-``write`` responses, chunked streaming
for SSE, and gzip/deflate body decoding.  PR 7 left that framing
duplicated (~120 lines); this module is now its single home — the
router and the replica subclass :class:`BaseHttpHandler` and differ
only in *dispatch* (execute locally vs. forward to the fleet), which
is exactly the divergence tpulint's R8 protocol-parity rule verifies.

Framing rules encoded here:

- Responses leave in one ``write`` (status + headers + body), with
  Nagle disabled — a multi-write response interacts with delayed ACKs
  for ~40ms stalls.
- A POST body is always drained before responding (an unconsumed body
  would be parsed as the next request on the keep-alive socket); a
  body that cannot be read (bad Content-Length / encoding) answers 400
  and drops the connection, whose stream position is undefined.
- Chunked transfer framing is HTTP/1.1; a 1.0 client gets streamed
  bodies raw, delimited by connection close.
- Streaming writes (``_send_stream_start`` / ``_send_chunk`` /
  ``_end_chunks``) convert a dead DOWNSTREAM socket into
  :class:`ClientGone` so relay/generate loops can distinguish "my
  client hung up" from an upstream failure.
"""

import gzip
import json
import socketserver
import zlib

#: One status line per code either surface can emit.  This map is the
#: single source of truth for both tiers (R4 checks every ServerError
#: code appears here; R8 checks it stays a superset of the gRPC code
#: map): a code missing from it silently degrades to the blanket 500
#: line on the wire.
_STATUS_LINE = {
    200: b"HTTP/1.1 200 OK\r\n",
    400: b"HTTP/1.1 400 Bad Request\r\n",
    404: b"HTTP/1.1 404 Not Found\r\n",
    405: b"HTTP/1.1 405 Method Not Allowed\r\n",
    409: b"HTTP/1.1 409 Conflict\r\n",
    422: b"HTTP/1.1 422 Unprocessable Entity\r\n",
    429: b"HTTP/1.1 429 Too Many Requests\r\n",
    500: b"HTTP/1.1 500 Internal Server Error\r\n",
    501: b"HTTP/1.1 501 Not Implemented\r\n",
    502: b"HTTP/1.1 502 Bad Gateway\r\n",
    503: b"HTTP/1.1 503 Service Unavailable\r\n",
    504: b"HTTP/1.1 504 Gateway Timeout\r\n",
}


class ClientGone(Exception):
    """The downstream client hung up mid-stream.  Raised by the
    streaming writers instead of the raw ``ConnectionError`` so a relay
    loop cannot mistake its own dead client for an upstream failure
    (the router would otherwise mark a healthy replica unreachable)."""


class _Headers:
    """Case-insensitive header view over a plain dict of lowercased
    keys."""

    __slots__ = ("_d",)

    def __init__(self, d):
        self._d = d

    def get(self, key, default=None):
        return self._d.get(key.lower(), default)


class BaseHttpHandler(socketserver.StreamRequestHandler):
    """The shared request loop + response plumbing.  Subclasses provide
    ``_dispatch(method)`` (and ``server_token`` for the Server:
    header); everything on the wire below the route table lives here.
    """

    # Send responses in one TCP segment: without NODELAY the write
    # would interact with delayed ACKs for ~40ms stalls.
    disable_nagle_algorithm = True

    #: The Server: response header value.
    server_token = b"tpu-triton-server"

    # -- request loop ------------------------------------------------------

    def handle(self):
        rfile = self.rfile
        while True:
            line = rfile.readline(65537)
            if not line:
                return
            if line in (b"\r\n", b"\n"):
                continue
            try:
                method, target, version = (
                    line.decode("latin-1").rstrip("\r\n").split(" ", 2)
                )
            except ValueError:
                self._send(400, b'{"error": "malformed request line"}')
                return
            raw_headers = {}
            while True:
                h = rfile.readline(65537)
                if h in (b"\r\n", b"\n", b""):
                    break
                colon = h.find(b":")
                if colon > 0:
                    raw_headers[
                        h[:colon].decode("latin-1").strip().lower()
                    ] = h[colon + 1:].decode("latin-1").strip()
            self.headers = _Headers(raw_headers)
            self.path = target
            # chunked transfer framing is HTTP/1.1; a 1.0 client gets
            # streamed bodies raw, delimited by connection close
            self._chunked_ok = version != "HTTP/1.0"
            close = (
                raw_headers.get("connection", "").lower() == "close"
                or version == "HTTP/1.0"
            )
            self._body = None
            self._started = False
            try:
                if method == "POST":
                    try:
                        self._read_body()  # drain before any response
                    except (ValueError, OSError, EOFError, zlib.error) as e:
                        # body unreadable (bad Content-Length / encoding):
                        # respond, then drop the connection — the socket
                        # position is undefined for further requests
                        self._send_error_json(
                            "malformed request body: {}".format(e), 400
                        )
                        return
                    self._dispatch("POST")
                elif method == "GET":
                    self._dispatch("GET")
                else:
                    # unknown method: the body (if any) was not drained,
                    # so this connection cannot be reused
                    self._send(405, b'{"error": "unsupported method"}')
                    return
            except (BrokenPipeError, ConnectionResetError, ClientGone):
                return
            if close:
                return

    def _dispatch(self, method):
        raise NotImplementedError

    # -- body --------------------------------------------------------------

    def _read_body(self):
        """Read (once) and cache the request body.

        Always called before responding — an unconsumed body would be
        parsed as the start of the next request on this keep-alive
        socket.
        """
        if self._body is None:
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length) if length else b""
            encoding = self.headers.get("Content-Encoding")
            if encoding == "gzip":
                body = gzip.decompress(body)
            elif encoding == "deflate":
                body = zlib.decompress(body)
            self._body = body
        return self._body

    # -- unary responses ---------------------------------------------------

    def _send(self, code, body=b"", headers=None,
              content_type="application/json"):
        head = (
            _STATUS_LINE.get(code, _STATUS_LINE[500])
            + b"Server: " + self.server_token
            + b"\r\nContent-Type: "
            + content_type.encode("latin-1")
            + b"\r\nContent-Length: "
            + str(len(body)).encode("latin-1")
            + b"\r\n"
        )
        for key, val in (headers or {}).items():
            head += (
                key.encode("latin-1")
                + b": "
                + str(val).encode("latin-1")
                + b"\r\n"
            )
        # single write: status + headers + body in one segment
        self.wfile.write(head + b"\r\n" + body)

    def _send_json(self, obj, code=200, headers=None):
        self._send(code, json.dumps(obj).encode("utf-8"), headers)

    def _send_error_json(self, msg, code=400, headers=None):
        self._send_json({"error": msg}, code, headers)

    # -- streaming responses -----------------------------------------------

    def _send_stream_start(self, content_type="text/event-stream"):
        """Open a streaming 200 response; the body follows as
        ``_send_chunk`` frames ended by ``_end_chunks``.  Used by
        ``/generate_stream`` — token count is data-dependent, so
        Content-Length cannot be known up front and each token must
        leave the socket as its decode step produces it."""
        head = (
            _STATUS_LINE[200]
            + b"Server: " + self.server_token
            + b"\r\nContent-Type: "
            + content_type.encode("latin-1")
        )
        if self._chunked_ok:
            head += b"\r\nTransfer-Encoding: chunked\r\n\r\n"
        else:
            head += b"\r\nConnection: close\r\n\r\n"
        try:
            self.wfile.write(head)
        except (BrokenPipeError, ConnectionResetError, OSError) as e:
            raise ClientGone() from e

    def _ensure_started(self, content_type="text/event-stream"):
        if not self._started:
            self._send_stream_start(content_type)
            self._started = True

    def _send_chunk(self, data):
        """One streamed frame to the client, flushed immediately; a
        dead client raises :class:`ClientGone` so streaming loops can
        stop generating (or park resume state) instead of spinning."""
        try:
            if self._chunked_ok:
                data = (("%x\r\n" % len(data)).encode("latin-1")
                        + data + b"\r\n")
            self.wfile.write(data)
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError) as e:
            raise ClientGone() from e

    def _end_chunks(self):
        """Terminal zero-length chunk: the connection stays reusable
        (no-op for HTTP/1.0, whose end-of-body is the close)."""
        if self._chunked_ok:
            try:
                self.wfile.write(b"0\r\n\r\n")
            except (BrokenPipeError, ConnectionResetError, OSError) as e:
                raise ClientGone() from e
