"""Shared stdlib HTTP request framing for the replica frontend and the
fleet router.

Both ``tpuserver.http_frontend._Handler`` and
``tpuserver.router._RouterHandler`` speak the same hand-rolled
HTTP/1.1 dialect: request-line + header parsing with byte splits (the
stdlib ``BaseHTTPRequestHandler`` parses headers through the email
package at ~300us/request), one-``write`` responses, chunked streaming
for SSE, and gzip/deflate body decoding.  PR 7 left that framing
duplicated (~120 lines); this module is now its single home — the
router and the replica subclass :class:`BaseHttpHandler` and differ
only in *dispatch* (execute locally vs. forward to the fleet), which
is exactly the divergence tpulint's R8 protocol-parity rule verifies.

Framing rules encoded here:

- Responses leave in one ``write`` (status + headers + body), with
  Nagle disabled — a multi-write response interacts with delayed ACKs
  for ~40ms stalls.
- A POST body is always drained before responding (an unconsumed body
  would be parsed as the next request on the keep-alive socket); a
  body that cannot be read (bad Content-Length / encoding) answers 400
  and drops the connection, whose stream position is undefined.
- Chunked transfer framing is HTTP/1.1; a 1.0 client gets streamed
  bodies raw, delimited by connection close.
- Streaming writes (``_send_stream_start`` / ``_send_chunk`` /
  ``_end_chunks``) convert a dead DOWNSTREAM socket into
  :class:`ClientGone` so relay/generate loops can distinguish "my
  client hung up" from an upstream failure.
"""

import gzip
import json
import selectors
import socket
import socketserver
import threading
import zlib

#: One status line per code either surface can emit.  This map is the
#: single source of truth for both tiers (R4 checks every ServerError
#: code appears here; R8 checks it stays a superset of the gRPC code
#: map): a code missing from it silently degrades to the blanket 500
#: line on the wire.
_STATUS_LINE = {
    200: b"HTTP/1.1 200 OK\r\n",
    400: b"HTTP/1.1 400 Bad Request\r\n",
    404: b"HTTP/1.1 404 Not Found\r\n",
    405: b"HTTP/1.1 405 Method Not Allowed\r\n",
    409: b"HTTP/1.1 409 Conflict\r\n",
    422: b"HTTP/1.1 422 Unprocessable Entity\r\n",
    429: b"HTTP/1.1 429 Too Many Requests\r\n",
    500: b"HTTP/1.1 500 Internal Server Error\r\n",
    501: b"HTTP/1.1 501 Not Implemented\r\n",
    502: b"HTTP/1.1 502 Bad Gateway\r\n",
    503: b"HTTP/1.1 503 Service Unavailable\r\n",
    504: b"HTTP/1.1 504 Gateway Timeout\r\n",
}


class ClientGone(Exception):
    """The downstream client hung up mid-stream.  Raised by the
    streaming writers instead of the raw ``ConnectionError`` so a relay
    loop cannot mistake its own dead client for an upstream failure
    (the router would otherwise mark a healthy replica unreachable)."""


class _Headers:
    """Case-insensitive header view over a plain dict of lowercased
    keys."""

    __slots__ = ("_d",)

    def __init__(self, d):
        self._d = d

    def get(self, key, default=None):
        return self._d.get(key.lower(), default)


class BaseHttpHandler(socketserver.StreamRequestHandler):
    """The shared request loop + response plumbing.  Subclasses provide
    ``_dispatch(method)`` (and ``server_token`` for the Server:
    header); everything on the wire below the route table lives here.
    """

    # Send responses in one TCP segment: without NODELAY the write
    # would interact with delayed ACKs for ~40ms stalls.
    disable_nagle_algorithm = True

    #: The Server: response header value.
    server_token = b"tpu-triton-server"

    # -- request loop ------------------------------------------------------

    def handle(self):
        rfile = self.rfile
        while True:
            line = rfile.readline(65537)
            if not line:
                return
            if line in (b"\r\n", b"\n"):
                continue
            try:
                method, target, version = (
                    line.decode("latin-1").rstrip("\r\n").split(" ", 2)
                )
            except ValueError:
                self._send(400, b'{"error": "malformed request line"}')
                return
            raw_headers = {}
            while True:
                h = rfile.readline(65537)
                if h in (b"\r\n", b"\n", b""):
                    break
                colon = h.find(b":")
                if colon > 0:
                    raw_headers[
                        h[:colon].decode("latin-1").strip().lower()
                    ] = h[colon + 1:].decode("latin-1").strip()
            self.headers = _Headers(raw_headers)
            self.path = target
            # chunked transfer framing is HTTP/1.1; a 1.0 client gets
            # streamed bodies raw, delimited by connection close
            self._chunked_ok = version != "HTTP/1.0"
            close = (
                raw_headers.get("connection", "").lower() == "close"
                or version == "HTTP/1.0"
            )
            self._body = None
            self._started = False
            self._detached = False
            try:
                if method == "POST":
                    try:
                        self._read_body()  # drain before any response
                    except (ValueError, OSError, EOFError, zlib.error) as e:
                        # body unreadable (bad Content-Length / encoding):
                        # respond, then drop the connection — the socket
                        # position is undefined for further requests
                        self._send_error_json(
                            "malformed request body: {}".format(e), 400
                        )
                        return
                    self._dispatch("POST")
                elif method == "GET":
                    self._dispatch("GET")
                else:
                    # unknown method: the body (if any) was not drained,
                    # so this connection cannot be reused
                    self._send(405, b'{"error": "unsupported method"}')
                    return
            except (BrokenPipeError, ConnectionResetError, ClientGone):
                return
            if self._detached or close:
                # detached: the connection's ownership moved to an
                # SseRelayLoop — reading more requests off it here
                # would race the relay's writes on the same socket
                return

    def _dispatch(self, method):
        raise NotImplementedError

    # -- body --------------------------------------------------------------

    def _read_body(self):
        """Read (once) and cache the request body.

        Always called before responding — an unconsumed body would be
        parsed as the start of the next request on this keep-alive
        socket.
        """
        if self._body is None:
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length) if length else b""
            encoding = self.headers.get("Content-Encoding")
            if encoding == "gzip":
                body = gzip.decompress(body)
            elif encoding == "deflate":
                body = zlib.decompress(body)
            self._body = body
        return self._body

    # -- unary responses ---------------------------------------------------

    def _send(self, code, body=b"", headers=None,
              content_type="application/json"):
        head = (
            _STATUS_LINE.get(code, _STATUS_LINE[500])
            + b"Server: " + self.server_token
            + b"\r\nContent-Type: "
            + content_type.encode("latin-1")
            + b"\r\nContent-Length: "
            + str(len(body)).encode("latin-1")
            + b"\r\n"
        )
        for key, val in (headers or {}).items():
            head += (
                key.encode("latin-1")
                + b": "
                + str(val).encode("latin-1")
                + b"\r\n"
            )
        # single write: status + headers + body in one segment
        self.wfile.write(head + b"\r\n" + body)

    def _send_json(self, obj, code=200, headers=None):
        self._send(code, json.dumps(obj).encode("utf-8"), headers)

    def _send_error_json(self, msg, code=400, headers=None):
        self._send_json({"error": msg}, code, headers)

    # -- streaming responses -----------------------------------------------

    def _send_stream_start(self, content_type="text/event-stream"):
        """Open a streaming 200 response; the body follows as
        ``_send_chunk`` frames ended by ``_end_chunks``.  Used by
        ``/generate_stream`` — token count is data-dependent, so
        Content-Length cannot be known up front and each token must
        leave the socket as its decode step produces it."""
        head = (
            _STATUS_LINE[200]
            + b"Server: " + self.server_token
            + b"\r\nContent-Type: "
            + content_type.encode("latin-1")
        )
        if self._chunked_ok:
            head += b"\r\nTransfer-Encoding: chunked\r\n\r\n"
        else:
            head += b"\r\nConnection: close\r\n\r\n"
        try:
            self.wfile.write(head)
        except (BrokenPipeError, ConnectionResetError, OSError) as e:
            raise ClientGone() from e

    def _ensure_started(self, content_type="text/event-stream"):
        if not self._started:
            self._send_stream_start(content_type)
            self._started = True

    def _send_chunk(self, data):
        """One streamed frame to the client, flushed immediately; a
        dead client raises :class:`ClientGone` so streaming loops can
        stop generating (or park resume state) instead of spinning."""
        try:
            if self._chunked_ok:
                data = (("%x\r\n" % len(data)).encode("latin-1")
                        + data + b"\r\n")
            self.wfile.write(data)
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError) as e:
            raise ClientGone() from e

    def _end_chunks(self):
        """Terminal zero-length chunk: the connection stays reusable
        (no-op for HTTP/1.0, whose end-of-body is the close)."""
        if self._chunked_ok:
            try:
                self.wfile.write(b"0\r\n\r\n")
            except (BrokenPipeError, ConnectionResetError, OSError) as e:
                raise ClientGone() from e

    # -- socket detach (selector relay hand-off) ---------------------------

    def _detach_socket(self):
        """Dup the client socket out of the handler thread's ownership
        so an :class:`SseRelayLoop` can keep streaming on it after this
        handler returns.  Flushes any buffered response bytes first
        (the stream-start headers must hit the wire before the relay's
        frames), marks the request detached so ``handle()`` stops
        reading the shared connection, and returns the new socket
        object.  The caller's server must still skip the half-close in
        ``shutdown_request`` (a ``shutdown(SHUT_WR)`` on the original
        applies to the dup'd socket too)."""
        self.wfile.flush()
        sock = self.connection.dup()
        self._detached = True
        return sock


class _ChunkDecoder:
    """Incremental HTTP/1.1 chunked-transfer decoder: feed raw socket
    bytes, get body bytes back.  ``done`` latches once the terminal
    0-length chunk (and any trailers) has been consumed — a clean end
    of body, distinct from a connection drop mid-chunk."""

    __slots__ = ("_buf", "_remaining", "_state", "done")

    def __init__(self):
        self._buf = b""
        self._remaining = 0
        self._state = "size"
        self.done = False

    def feed(self, data):
        self._buf += data
        out = []
        while not self.done:
            if self._state == "size":
                i = self._buf.find(b"\r\n")
                if i < 0:
                    break
                line = self._buf[:i].split(b";", 1)[0].strip()
                self._buf = self._buf[i + 2:]
                size = int(line or b"0", 16)
                if size == 0:
                    self._state = "trailer"
                else:
                    self._remaining = size
                    self._state = "data"
            elif self._state == "data":
                if not self._buf:
                    break
                take = self._buf[:self._remaining]
                out.append(take)
                self._buf = self._buf[len(take):]
                self._remaining -= len(take)
                if self._remaining == 0:
                    self._state = "crlf"
            elif self._state == "crlf":
                if len(self._buf) < 2:
                    break
                self._buf = self._buf[2:]
                self._state = "size"
            else:  # trailer lines end at the first empty line
                i = self._buf.find(b"\r\n")
                if i < 0:
                    break
                line = self._buf[:i]
                self._buf = self._buf[i + 2:]
                if not line:
                    self.done = True
        return b"".join(out)


class RelayStream:
    """One detached SSE relay: an upstream socket already past its
    response headers, a client socket already past the stream-start
    headers, and the protocol adapter that turns upstream lines into
    client frames.  Every field is owned by the relay loop's single
    thread after :meth:`SseRelayLoop.adopt` — no locking.

    The adapter contract (``relay``):

    - ``on_line(line) -> (action, blocks)`` — one upstream SSE line
      (terminator stripped); ``blocks`` are pre-formatted SSE bytes to
      forward (the loop applies chunked framing), ``action`` is
      ``"continue"``, ``"final"`` or ``"error"`` (both terminal: the
      loop appends the chunked terminator and closes cleanly).
    - ``on_upstream_end()`` — upstream EOF/clean chunked end with no
      terminal event; the loop then closes the client WITHOUT the
      chunked terminator, which a resuming client reads as a dropped
      connection and reconnects through its resume path.
    - ``on_closed(reason)`` — exactly once, after both sockets are
      closed; releases the generation/replica/inflight accounting the
      detaching handler deferred.
    """

    __slots__ = ("upstream", "client", "relay", "chunked_out", "decoder",
                 "leftover", "linebuf", "outbuf", "closed", "terminal",
                 "paused", "writable_armed")

    def __init__(self, upstream, client, relay, leftover=b"",
                 chunked_in=True, chunked_out=True):
        self.upstream = upstream
        self.client = client
        self.relay = relay
        self.chunked_out = chunked_out
        self.decoder = _ChunkDecoder() if chunked_in else None
        self.leftover = leftover
        self.linebuf = b""
        self.outbuf = bytearray()
        self.closed = False
        self.terminal = None
        self.paused = False
        self.writable_armed = False


class SseRelayLoop:
    """A selector-driven relay for detached SSE streams: one daemon
    thread multiplexes thousands of idle token streams that would each
    pin a blocking thread under the stock ThreadingTCPServer relay
    (ROADMAP item 4's thread-per-connection ceiling).  The relay hot
    path was already enqueue-only (PR 15's AST pin on the journal
    writer), so the writer side degrades naturally to an event loop.

    Streams enter through :meth:`adopt` from handler threads; all
    socket work happens on the loop thread.  Backpressure: a slow
    client's outbound buffer pauses upstream reads past
    ``HIGH_WATER`` and resumes below ``LOW_WATER``.
    """

    #: outbound buffer bounds for one stream: pause upstream reads at
    #: HIGH_WATER bytes queued, resume once the client drains below
    #: LOW_WATER — an unbounded buffer would let one dead-slow client
    #: hold token history for its whole generation in memory
    HIGH_WATER = 1 << 20
    LOW_WATER = 1 << 16

    def __init__(self, name="sse-relay"):
        self._name = name
        self._selector = selectors.DefaultSelector()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._selector.register(self._wake_r, selectors.EVENT_READ, None)
        self._lock = threading.Lock()
        self._pending = []       # guarded-by: _lock
        self._thread = None      # guarded-by: _lock
        self._stopping = False   # guarded-by: _lock
        self._active = 0         # guarded-by: _lock
        self._adopted_total = 0  # guarded-by: _lock
        self._closed_total = 0   # guarded-by: _lock

    # -- handler-thread surface --------------------------------------------

    def adopt(self, stream):
        """Hand a :class:`RelayStream` to the loop (lazy-starting the
        loop thread on first use).  Raises ``RuntimeError`` after
        :meth:`stop` — the caller falls back to its threaded relay."""
        with self._lock:
            if self._stopping:
                raise RuntimeError("relay loop is stopped")
            self._pending.append(stream)
            self._adopted_total += 1
            self._active += 1
            starter = None
            if self._thread is None:
                starter = threading.Thread(
                    target=self._run, name=self._name, daemon=True)
                self._thread = starter
        if starter is not None:
            starter.start()
        self._wake()

    def stats(self):
        with self._lock:
            return {
                "streams": self._active,
                "adopted_total": self._adopted_total,
                "closed_total": self._closed_total,
            }

    def stop(self):
        """Stop the loop and close every adopted stream (reason
        ``"stopped"``); joins the loop thread."""
        with self._lock:
            self._stopping = True
            thread = self._thread
        self._wake()
        if thread is not None:
            thread.join(timeout=5.0)
        else:
            # never started: release the selector + wake pipe here
            self._teardown()

    def _wake(self):
        try:
            self._wake_w.send(b"w")
        except OSError:
            pass  # loop already tore the wake pipe down

    # -- loop thread -------------------------------------------------------

    def _run(self):
        while True:
            with self._lock:
                stopping = self._stopping
                pending, self._pending = self._pending, []
            if stopping:
                for stream in pending:
                    self._close_stream(stream, "stopped")
                break
            for stream in pending:
                self._register(stream)
            for key, mask in self._selector.select(timeout=0.5):
                stream = key.data
                if stream is None:
                    try:
                        self._wake_r.recv(65536)
                    except OSError:
                        pass
                    continue
                if stream.closed:
                    continue
                try:
                    if mask & selectors.EVENT_WRITE:
                        self._flush_client(stream)
                    if (mask & selectors.EVENT_READ) and not stream.closed:
                        if key.fileobj is stream.client:
                            self._client_readable(stream)
                        else:
                            self._upstream_readable(stream)
                except (OSError, ValueError):
                    self._close_stream(stream, "relay-error")
        self._teardown()

    def _teardown(self):
        for key in list(self._selector.get_map().values()):
            if key.data is not None:
                self._close_stream(key.data, "stopped")
        try:
            self._wake_r.close()
            self._wake_w.close()
        except OSError:
            pass
        self._selector.close()

    def _register(self, stream):
        stream.upstream.setblocking(False)
        stream.client.setblocking(False)
        try:
            self._selector.register(
                stream.upstream, selectors.EVENT_READ, stream)
            self._selector.register(
                stream.client, selectors.EVENT_READ, stream)
        except (OSError, ValueError):
            self._close_stream(stream, "relay-error")
            return
        leftover, stream.leftover = stream.leftover, b""
        if leftover:
            try:
                self._feed(stream, leftover)
            except (OSError, ValueError):
                self._close_stream(stream, "relay-error")

    # -- upstream side -----------------------------------------------------

    def _upstream_readable(self, stream):
        while not stream.closed and not stream.paused:
            try:
                data = stream.upstream.recv(65536)
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                data = b""
            if not data:
                self._upstream_end(stream)
                return
            if not self._feed(stream, data):
                return

    def _feed(self, stream, data):
        """Decode body framing, split SSE lines, drive the adapter.
        Returns False once the stream reached a terminal state."""
        payload = (stream.decoder.feed(data) if stream.decoder is not None
                   else data)
        stream.linebuf += payload
        while True:
            i = stream.linebuf.find(b"\n")
            if i < 0:
                break
            line = stream.linebuf[:i].rstrip(b"\r")
            stream.linebuf = stream.linebuf[i + 1:]
            action, blocks = stream.relay.on_line(line)
            for block in blocks:
                self._queue_out(stream, block)
            if stream.closed:
                return False  # queueing found the client gone
            if action != "continue":
                self._finish(stream, action)
                return False
        if stream.decoder is not None and stream.decoder.done:
            self._upstream_end(stream)
            return False
        if stream.paused:
            # mid-feed overflow: stash nothing — recv stops above; the
            # already-buffered linebuf waits for the drain to resume
            return not stream.closed
        return not stream.closed

    def _upstream_end(self, stream):
        """Upstream EOF with no terminal event: flush what the client
        is owed, then close WITHOUT the chunked terminator so the
        resuming client treats it as a dropped connection."""
        stream.relay.on_upstream_end()
        self._drop_upstream(stream)
        stream.terminal = "upstream-died"
        self._flush_client(stream)

    def _finish(self, stream, action):
        """Terminal event relayed: append the chunked terminator, drop
        the upstream leg now, and close the client once its buffer
        drains."""
        self._drop_upstream(stream)
        if stream.chunked_out:
            stream.outbuf += b"0\r\n\r\n"
        stream.terminal = action
        self._flush_client(stream)

    def _drop_upstream(self, stream):
        sock = stream.upstream
        stream.upstream = None
        if sock is None:
            return
        try:
            self._selector.unregister(sock)
        except (KeyError, ValueError):
            pass
        try:
            sock.close()
        except OSError:
            pass

    # -- client side -------------------------------------------------------

    def _client_readable(self, stream):
        """SSE clients never send mid-stream: readable means EOF/RST
        (hung up) or stray bytes we drain and ignore."""
        try:
            data = stream.client.recv(65536)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            data = b""
        if not data:
            self._close_stream(stream, "client-gone")

    def _queue_out(self, stream, block):
        if stream.chunked_out:
            stream.outbuf += ("%x\r\n" % len(block)).encode("latin-1")
            stream.outbuf += block
            stream.outbuf += b"\r\n"
        else:
            stream.outbuf += block
        self._flush_client(stream)

    def _flush_client(self, stream):
        if stream.closed:
            return
        while stream.outbuf:
            try:
                sent = stream.client.send(bytes(stream.outbuf[:65536]))
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self._close_stream(stream, "client-gone")
                return
            if sent == 0:
                self._close_stream(stream, "client-gone")
                return
            del stream.outbuf[:sent]
        if stream.outbuf:
            self._arm_writable(stream, True)
            if (len(stream.outbuf) >= self.HIGH_WATER
                    and not stream.paused):
                stream.paused = True
                self._arm_upstream(stream, False)
        else:
            self._arm_writable(stream, False)
            if stream.terminal is not None:
                self._close_stream(stream, stream.terminal)
                return
            if stream.paused and len(stream.outbuf) <= self.LOW_WATER:
                stream.paused = False
                self._arm_upstream(stream, True)

    def _arm_writable(self, stream, want):
        if want == stream.writable_armed or stream.closed:
            return
        mask = selectors.EVENT_READ | (
            selectors.EVENT_WRITE if want else 0)
        try:
            self._selector.modify(stream.client, mask, stream)
            stream.writable_armed = want
        except (KeyError, ValueError, OSError):
            pass

    def _arm_upstream(self, stream, want):
        if stream.upstream is None:
            return
        try:
            if want:
                self._selector.register(
                    stream.upstream, selectors.EVENT_READ, stream)
            else:
                self._selector.unregister(stream.upstream)
        except (KeyError, ValueError, OSError):
            pass

    def _close_stream(self, stream, reason):
        if stream.closed:
            return
        stream.closed = True
        self._drop_upstream(stream)
        try:
            self._selector.unregister(stream.client)
        except (KeyError, ValueError):
            pass
        try:
            stream.client.close()
        except OSError:
            pass
        with self._lock:
            self._active -= 1
            self._closed_total += 1
        try:
            stream.relay.on_closed(reason)
        except Exception:  # noqa: BLE001 — adapter cleanup must never
            # take the shared loop (and every other stream) down
            pass
