"""First-class observability: a dependency-free Prometheus-text-format
metrics registry for every serving tier.

Role of the reference server's metrics plane (the ``:8002/metrics``
endpoint perf_analyzer's ``--collect-metrics`` scrapes,
metrics_manager.h:44-91), rebuilt for this stack: counters, gauges,
and histograms with explicit buckets, optional labels, and a single
:meth:`MetricsRegistry.render` producing the ``# HELP``/``# TYPE`` +
sample exposition any Prometheus scraper (or the fleet router's
aggregator) consumes.  No client library dependency — the text format
is the contract.

Two registration shapes, chosen by where the numbers live:

- **Owned instruments** (:meth:`~MetricsRegistry.counter` /
  :meth:`~MetricsRegistry.gauge` / :meth:`~MetricsRegistry.histogram`)
  for values produced *at* the instrumentation site — request counts,
  latency observations.  Multi-writer instruments take a tiny
  per-child lock on update: a lock-free ``+=`` is a non-atomic
  read-modify-write whose stale store can roll a counter *backwards*
  mid-race, which a scraper (and the fleet aggregator's reset
  detection) would misread as a process restart.  The decode
  scheduler's histograms opt out via ``single_writer=True`` — the
  loop is their only writer, so plain adds are exact and the loop
  never pays a lock per step (open item 3's hot-path lesson).
- **Collectors** (:meth:`~MetricsRegistry.register_collector`) for
  values that already exist as authoritative counters elsewhere —
  ``DecodeScheduler.stats()``, ``FleetRouter.stats()``, the fleet
  supervisor's healing counters.  The collector reads them at scrape
  time, so the registry is a *view*, never a second account of the
  same event (test-pinned: registry and scheduler stats must agree).

Every family name must be declared in :data:`CATALOG` (name -> (type,
help)): the registry rejects unknown names, and the doc-drift test
pins every catalog name into ``docs/observability.md`` — the same
code<->registry<->docs triangle ``faults.POINTS`` holds for fault
injection.

:func:`parse_prometheus_text` is the minimal parser the fleet
router's churn-safe aggregator and the chaos soaks share; tests carry
their own in-test parser so the exposition format itself stays
pinned from the outside.
"""

import bisect
import re
import threading

__all__ = [
    "CATALOG",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "is_cumulative",
    "parse_prometheus_text",
]

#: The metric catalog: every family either tier may expose, name ->
#: (type, help).  Code registers only names declared here (the
#: registry enforces it) and docs/observability.md must backtick every
#: name (doc-drift test in tests/test_static_analysis.py) — so the
#: scrape surface, the code, and the ops docs cannot drift apart.
CATALOG = {
    # -- replica core (every request, both frontends) ----------------------
    "tpu_requests_total": (
        "counter",
        "Requests executed, by verb (infer / stream_infer)."),
    "tpu_request_seconds": (
        "histogram",
        "End-to-end request latency by verb, seconds (streamed verbs "
        "measure submit-to-terminal-event)."),
    "tpu_request_errors_total": (
        "counter",
        "Typed request failures by verb and HTTP status code (429 = "
        "shed, 504 = deadline, 503 = draining/shutdown, ...)."),
    "tpu_inflight_requests": (
        "gauge", "Requests currently executing in the core."),
    # -- shared-memory data plane ------------------------------------------
    "tpu_shm_regions": (
        "gauge",
        "Registered shared-memory regions, by kind (system / cuda / "
        "xla; server-owned KV exports count as xla)."),
    "tpu_shm_bytes_read_total": (
        "counter",
        "Bytes materialized from registered shared-memory regions "
        "(request inputs resolved by reference; device-resident "
        "zero-copy reads count their logical tensor size)."),
    "tpu_shm_bytes_written_total": (
        "counter",
        "Bytes written into registered shared-memory regions (shm-"
        "delivered outputs and token-ring slots)."),
    "tpu_shm_ring_torn_total": (
        "counter",
        "Token-ring slot reads that observed a torn or stale seqlock "
        "word and fell back to the event's in-band payload (requests "
        "opting in via shm_ring_seq_base; process-wide)."),
    # -- decode scheduler (continuous batching) ----------------------------
    "tpu_scheduler_admissions_total": (
        "counter",
        "Generations admitted into a cache slot (prefill-on-admit), "
        "per model; re-admissions after a supervised restart count."),
    "tpu_scheduler_tokens_total": (
        "counter", "Tokens emitted to streams, per model."),
    "tpu_scheduler_restarts_total": (
        "counter",
        "Supervised decode-loop restarts, per model — the flapping "
        "signal ops rotate on."),
    "tpu_scheduler_quarantined_total": (
        "counter",
        "Slots quarantined for non-finite output (poisoned "
        "generations), per model."),
    "tpu_scheduler_replay_hits_total": (
        "counter",
        "Resume requests served from the replay buffer, per model."),
    "tpu_scheduler_live_streams": (
        "gauge", "Live (pending + slotted) generations, per model."),
    "tpu_scheduler_pending": (
        "gauge", "Generations waiting for a slot, per model."),
    "tpu_scheduler_queue_wait_seconds": (
        "histogram",
        "Time from submit to slot admission (the scheduler queue "
        "bucket), per model, seconds."),
    "tpu_scheduler_step_seconds": (
        "histogram",
        "Batched decode-step dispatch latency, per model, seconds."),
    "tpu_scheduler_codel_sheds_total": (
        "counter",
        "Admissions shed by the adaptive (CoDel-style) queue "
        "controller — sojourn above target for a full control "
        "interval — per model.  The fixed max_pending cliff sheds "
        "count in tpu_request_errors_total{code=429} as before."),
    "tpu_scheduler_codel_shedding": (
        "gauge",
        "Whether the adaptive queue-shed controller is actively "
        "shedding (1) or the admission queue's sojourn is under "
        "target (0), per model."),
    # -- paged KV + radix prefix cache -------------------------------------
    "tpu_prefix_cache_hits_total": (
        "counter",
        "Prompt tokens served from shared radix-cache pages at "
        "admission (skipped prefill), per model."),
    "tpu_prefix_cache_misses_total": (
        "counter",
        "Prompt tokens actually prefilled at admission (cold or "
        "unshared), per model."),
    "tpu_prefix_cache_evictions_total": (
        "counter",
        "KV pages evicted from the radix prefix cache under memory "
        "pressure (LRU, unpinned branches only), per model."),
    "tpu_kv_pages_total": (
        "gauge", "KV page pool size, per model."),
    "tpu_kv_pages_free": (
        "gauge", "KV pages on the free list, per model."),
    "tpu_kv_pages_cached": (
        "gauge",
        "KV pages held only by the radix prefix cache (unpinned, "
        "evictable), per model."),
    # -- speculative decoding ----------------------------------------------
    "tpu_spec_tokens_proposed_total": (
        "counter",
        "Draft tokens proposed by the n-gram speculative drafter and "
        "fed through batched verify steps, per model."),
    "tpu_spec_tokens_accepted_total": (
        "counter",
        "Draft tokens whose greedy argmax matched and were emitted "
        "(token-identical to single-token decoding), per model."),
    "tpu_spec_rollbacks_total": (
        "counter",
        "Speculative steps that rejected at least one draft token "
        "and rolled the slot's KV write cursor back, per model."),
    "tpu_spec_steps_total": (
        "counter",
        "Batched decode steps that carried at least one draft token "
        "into the multi-token verify path, per model."),
    "tpu_spec_accept_per_step": (
        "gauge",
        "Lifetime mean tokens emitted per speculative step (bonus + "
        "accepted drafts; 1.0 is the non-speculative bound), per "
        "model."),
    # -- fleet router ------------------------------------------------------
    "tpu_router_failovers_total": (
        "counter", "Requests re-routed to another replica."),
    "tpu_router_handoffs_total": (
        "counter",
        "Mid-generation cross-replica handoffs (token-identical "
        "re-admission on a live replica)."),
    "tpu_router_resumed_streams_total": (
        "counter", "Client resumes served from the router's buffer."),
    "tpu_router_shed_total": (
        "counter", "Requests shed at the router's in-flight cap."),
    "tpu_router_inflight_requests": (
        "gauge", "Requests currently forwarded by the router."),
    "tpu_router_generations": (
        "gauge", "Generations live in the router's sticky registry."),
    "tpu_router_replica_eligible": (
        "gauge",
        "Routing eligibility per replica (1 = receives traffic)."),
    "tpu_router_replica_load": (
        "gauge",
        "Routing load score per replica (probe load + router-local "
        "in-flight)."),
    "tpu_router_affinity_routed_total": (
        "counter",
        "Generation admissions routed to their prompt prefix's warm "
        "(affine) replica — the radix cache was already primed."),
    "tpu_router_ejections_total": (
        "counter",
        "Gray-failure soft-ejections: replicas routed around because "
        "their recent p90 was an outlier against the fleet median "
        "(they keep answering health probes — that is what makes the "
        "failure gray)."),
    "tpu_router_hedges_total": (
        "counter",
        "Hedged unary attempts by outcome: won = the hedge's response "
        "was used, lost = the primary answered after the hedge fired, "
        "cancelled = the hedge was abandoned in flight."),
    "tpu_router_replica_state": (
        "gauge",
        "Routing state per replica: one sample per replica whose "
        "'state' label is ok / soft-ejected / draining / unreachable "
        "/ ineligible / removed (value always 1) — distinguishes a "
        "gray incident from a planned drain from a dead process."),
    "tpu_router_replica_p90_seconds": (
        "gauge",
        "Rolling per-verb p90 latency per replica from the router's "
        "gray-failure digest (fixed-window, completed requests only; "
        "hedge losers excluded), seconds."),
    # -- router HA: crash journal + warm standby ---------------------------
    "tpu_router_journal_records_total": (
        "counter",
        "Records written to the crash-durable generation journal "
        "(bind/home/ev/fin/drop; enqueued lock-free on the relay "
        "path, framed + fsynced by the writer thread)."),
    "tpu_router_journal_bytes_total": (
        "counter",
        "Bytes appended to the generation journal (length-prefixed + "
        "checksummed frames)."),
    "tpu_router_journal_fsyncs_total": (
        "counter",
        "fsync batches the journal writer issued (many records "
        "amortize into one fsync)."),
    "tpu_router_recovered_generations_total": (
        "counter",
        "Generations rebuilt from the journal: boot-time recovery on "
        "a restarted router plus the warm standby's continuous "
        "tailing — the state that turns a marked (gen~offset/seq) "
        "resume from a typed 404 into a served splice."),
    "tpu_router_takeovers_total": (
        "counter",
        "Standby-to-active promotions this router performed (the "
        "warm-standby takeover signal: POST /router/promote, SIGUSR1, "
        "or the fleet supervisor on active-router death)."),
    # -- horizontal router tier: gen-id partitioning -----------------------
    "tpu_router_partition_owned_total": (
        "counter",
        "Generation admissions this router served because the "
        "generation id hashed into its own partition."),
    "tpu_router_partition_forwarded_total": (
        "counter",
        "Wrong-partition requests this router thin-proxied to the "
        "owning peer (one extra in-tier hop; clients carrying the "
        "full tier in fallback_urls mostly dial the owner directly)."),
    "tpu_router_partition_moved_total": (
        "counter",
        "Generations whose owning partition URL changed under an "
        "adopted partition-map epoch (standby promoted INTO a dead "
        "active's partition, or a respawn on a new port)."),
    "tpu_router_partition_epoch": (
        "gauge",
        "Monotonic epoch of the partition map this router is serving "
        "under (bumped by the supervisor on every broadcast; routers "
        "adopt strictly newer epochs only)."),
    # -- disaggregated prefill/decode (phase-split serving) ----------------
    "tpu_disagg_splits_total": (
        "counter",
        "Generations served phase-split: prefill leg on a prefill "
        "replica, KV pages exported, decode leg attached on a decode "
        "replica (no re-prefill)."),
    "tpu_disagg_fallbacks_total": (
        "counter",
        "Phase-split admissions that degraded to the fused path, by "
        "reason (no_prefill_replica, prefill_rejected, prefill_died, "
        "descriptor_missing, descriptor_conflict, "
        "descriptor_unreachable, prefill_died_after_token). Every "
        "fallback is token-identical to a fused run."),
    "tpu_disagg_transfers_total": (
        "counter",
        "KV-export descriptors fetched for cross-replica attach "
        "(one-shot claim per generation)."),
    "tpu_disagg_transfer_bytes_total": (
        "counter",
        "Bytes of exported KV cache referenced by fetched descriptors "
        "(host-synced on the prefill replica at descriptor time)."),
    "tpu_disagg_transfer_seconds_total": (
        "counter",
        "Wall time spent fetching KV-export descriptors (includes the "
        "prefill replica's device-to-host sync of the region)."),
    "tpu_disagg_prefill_queue_seconds_total": (
        "counter",
        "Wall time from prefill-leg dispatch to its first (and only) "
        "token — prefill queue + prefill compute as seen by the "
        "router."),
    "tpu_disagg_phase_queue_depth": (
        "gauge",
        "Queued plus live work per fleet phase ('phase' label: "
        "prefill / decode / fused) from the router's health "
        "snapshots."),
    # -- fleet supervisor (process-level healing) --------------------------
    "tpu_fleet_replica_restarts_total": (
        "counter", "Replica processes healed by the supervisor."),
    "tpu_fleet_scale_up_total": (
        "counter", "Elastic scale-up events."),
    "tpu_fleet_scale_down_total": (
        "counter", "Elastic scale-down events."),
    "tpu_fleet_retired_replicas_total": (
        "counter",
        "Replicas retired after exhausting their restart budget."),
    "tpu_fleet_replicas_up": (
        "gauge", "Replica processes currently up and routed."),
    # -- supervisor crash durability (manifest + adoption) -----------------
    "tpu_supervisor_adoptions_total": (
        "counter",
        "Live children (replicas and routers) ADOPTED by a restarted "
        "supervisor from its fleet-state manifest instead of being "
        "respawned (pid + start token + spawn nonce all matched)."),
    "tpu_supervisor_manifest_records_total": (
        "counter",
        "Records appended to the fleet-state manifest (spawn/restart/"
        "retire/scale/promote/config/checkpoint) by the off-hot-path "
        "writer thread."),
    "tpu_supervisor_clean_handovers_total": (
        "counter",
        "Graceful supervisor handovers: manifest checkpointed, "
        "single-writer lock released, children LEFT SERVING for a "
        "successor to adopt."),
    "tpu_supervisor_stale_children_reaped_total": (
        "counter",
        "Manifest rows whose process failed the adoption contract "
        "(dead pid, reused pid, nonce mismatch, unreachable health) "
        "and were reaped-then-respawned instead of adopted."),
}

#: Default latency buckets (seconds): spans the ~60us simple-model hot
#: path through multi-second generation tails.
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def _escape_label(value):
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_value(value):
    """Prometheus sample value: integral floats render as integers so
    counters read naturally; everything else as repr-precision float."""
    try:
        if float(value) == int(value):
            return str(int(value))
    except (OverflowError, ValueError):
        pass
    return repr(float(value))


def _render_labels(labels):
    if not labels:
        return ""
    return "{" + ",".join(
        '{}="{}"'.format(k, _escape_label(v)) for k, v in labels) + "}"


class Counter:
    """One monotonically non-decreasing sample.  ``inc`` takes a
    per-child lock: an unlocked ``+=`` is a LOAD/STORE pair whose
    stale store can visibly roll the value backwards under concurrent
    writers — a fake counter reset to any scraper.  The lock is
    per-child and uncontended on the paths that use it (never the
    decode loop)."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, amount=1):
        with self._lock:
            self._value += amount

    @property
    def value(self):
        # a bare attribute read is one atomic load; no lock needed
        return self._value


class Gauge:
    """One point-in-time sample (``set`` is a single atomic store;
    ``inc``/``dec`` read-modify-write under the child lock)."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0

    def set(self, value):
        self._value = value

    def inc(self, amount=1):
        with self._lock:
            self._value += amount

    def dec(self, amount=1):
        with self._lock:
            self._value -= amount

    @property
    def value(self):
        return self._value


class Histogram:
    """Cumulative-bucket histogram with explicit upper bounds.

    ``observe`` takes the child lock by default (multi-writer request
    paths); a ``single_writer=True`` child skips it — exact without a
    lock when one thread owns every observe, which is how the decode
    loop stamps its step/queue histograms without paying a lock per
    step.  A render racing an observe may see the new bucket count
    before the new ``_sum`` — scrape-level skew every cumulative
    histogram tolerates by design.
    """

    __slots__ = ("buckets", "_counts", "_sum", "_lock")

    def __init__(self, buckets, single_writer=False):
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self.buckets = bounds
        self._counts = [0] * (len(bounds) + 1)  # [-1] is +Inf
        self._sum = 0.0
        self._lock = None if single_writer else threading.Lock()

    def observe(self, value):
        value = float(value)
        idx = bisect.bisect_left(self.buckets, value)
        lock = self._lock
        if lock is None:
            self._counts[idx] += 1
            self._sum += value
        else:
            with lock:
                self._counts[idx] += 1
                self._sum += value

    def snapshot(self):
        """(cumulative_bucket_counts_with_inf, sum, count)."""
        counts = list(self._counts)
        cumulative = []
        running = 0
        for c in counts:
            running += c
            cumulative.append(running)
        return cumulative, self._sum, running


class _Family:
    """One metric family: name, declared type, and a child instrument
    per label-value tuple.  Child creation is rare (first request with
    a new label set) and takes the family lock; the hot path holds a
    child reference and never touches the family again."""

    def __init__(self, name, kind, help_text, labelnames, buckets=None,
                 single_writer=False):
        self.name = name
        self.kind = kind
        self.help = help_text
        self.labelnames = tuple(labelnames)
        self.buckets = buckets
        self.single_writer = single_writer
        self._lock = threading.Lock()
        self._children = {}  # label-values tuple -> instrument  # guarded-by: _lock

    def _make_child(self):
        if self.kind == "counter":
            return Counter()
        if self.kind == "gauge":
            return Gauge()
        return Histogram(self.buckets or DEFAULT_BUCKETS,
                         single_writer=self.single_writer)

    def labels(self, **labelvalues):
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                "family '{}' takes labels {}, got {}".format(
                    self.name, self.labelnames, sorted(labelvalues)))
        key = tuple(str(labelvalues[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._make_child()
                self._children[key] = child
            return child

    def child(self):
        """The label-less singleton child (families with no labels)."""
        if self.labelnames:
            raise ValueError(
                "family '{}' requires labels {}".format(
                    self.name, self.labelnames))
        return self.labels()

    def render(self, lines):
        lines.append("# HELP {} {}".format(self.name, self.help))
        lines.append("# TYPE {} {}".format(self.name, self.kind))
        with self._lock:
            children = list(self._children.items())
        for key, child in children:
            labels = list(zip(self.labelnames, key))
            if self.kind in ("counter", "gauge"):
                lines.append("{}{} {}".format(
                    self.name, _render_labels(labels),
                    _fmt_value(child.value)))
            else:
                cumulative, total, count = child.snapshot()
                for bound, cum in zip(
                        list(child.buckets) + ["+Inf"], cumulative):
                    le = ("+Inf" if bound == "+Inf"
                          else _fmt_value(bound))
                    lines.append("{}_bucket{} {}".format(
                        self.name,
                        _render_labels(labels + [("le", le)]), cum))
                lines.append("{}_sum{} {}".format(
                    self.name, _render_labels(labels),
                    _fmt_value(total)))
                lines.append("{}_count{} {}".format(
                    self.name, _render_labels(labels), count))


class MetricsRegistry:
    """The per-process family registry + renderer.

    Families register idempotently: a second registration of the same
    name returns the existing family (so every model can ask for the
    shared scheduler histograms), but a type or label-shape mismatch
    is a hard error — one name, one meaning.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._families = {}    # name -> _Family  # guarded-by: _lock
        self._collectors = []  # callables        # guarded-by: _lock

    def _register(self, name, kind, labelnames, buckets=None,
                  single_writer=False):
        entry = CATALOG.get(name)
        if entry is None:
            raise ValueError(
                "metric '{}' is not declared in tpuserver.metrics."
                "CATALOG — declare it there (and document it in "
                "docs/observability.md) first".format(name))
        declared_kind, help_text = entry
        if declared_kind != kind:
            raise ValueError(
                "metric '{}' is declared as a {} in CATALOG, not a "
                "{}".format(name, declared_kind, kind))
        with self._lock:
            family = self._families.get(name)
            if family is not None:
                if (family.kind != kind
                        or family.labelnames != tuple(labelnames)):
                    raise ValueError(
                        "metric '{}' re-registered with a different "
                        "shape".format(name))
                return family
            family = _Family(name, kind, help_text, labelnames, buckets,
                             single_writer=single_writer)
            self._families[name] = family
            return family

    def counter(self, name, labelnames=()):
        return self._register(name, "counter", labelnames)

    def gauge(self, name, labelnames=()):
        return self._register(name, "gauge", labelnames)

    def histogram(self, name, labelnames=(), buckets=None,
                  single_writer=False):
        """``single_writer=True`` children skip the per-observe lock:
        ONLY for families where one thread owns every observe (the
        decode loop's per-model histograms)."""
        return self._register(name, "histogram", labelnames,
                              buckets=buckets or DEFAULT_BUCKETS,
                              single_writer=single_writer)

    def register_collector(self, fn):
        """Register a scrape-time collector: ``fn()`` returns an
        iterable of ``(name, samples)`` where ``samples`` is a list of
        ``(labels_dict, value)`` and ``name`` is a CATALOG family.
        Collectors are how authoritative counters that live elsewhere
        (scheduler stats, router stats) surface without a second
        account of the same events."""
        with self._lock:
            self._collectors.append(fn)

    def render(self):
        """The Prometheus text exposition, trailing newline included."""
        with self._lock:
            families = list(self._families.values())
            collectors = list(self._collectors)
        lines = []
        rendered = set()
        for family in families:
            family.render(lines)
            rendered.add(family.name)
        for fn in collectors:
            try:
                emitted = list(fn())
            except Exception:  # noqa: BLE001 — observability must not
                # take the serving surface down with a dying collector
                continue
            for name, samples in emitted:
                entry = CATALOG.get(name)
                if entry is None or name in rendered:
                    continue  # undeclared or double-declared family
                rendered.add(name)
                kind, help_text = entry
                lines.append("# HELP {} {}".format(name, help_text))
                lines.append("# TYPE {} {}".format(name, kind))
                for labels, value in samples:
                    lines.append("{}{} {}".format(
                        name, _render_labels(sorted(labels.items())),
                        _fmt_value(value)))
        return "\n".join(lines) + "\n" if lines else ""


# -- the shared minimal parser ----------------------------------------------

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def is_cumulative(name, kind):
    """Whether a family's samples are cumulative (aggregate churn-safe
    across process restarts): declared counters and histograms, plus
    the untyped ``*_total``/``*_count`` compatibility families
    (``nv_inference_count``).  The ONE definition the fleet
    aggregator and the chaos soak's monotonicity check share."""
    if kind in ("counter", "histogram"):
        return True
    return kind is None and name.endswith(("_total", "_count"))


def _unescape_label(value):
    # a single left-to-right scan: sequential str.replace would decode
    # an escaped backslash followed by 'n' ("\\\\n") into a newline
    out = []
    i = 0
    while i < len(value):
        ch = value[i]
        if ch == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
            if nxt in ('"', "\\"):
                out.append(nxt)
                i += 2
                continue
        out.append(ch)
        i += 1
    return "".join(out)


def parse_prometheus_text(text):
    """Parse an exposition into ``{family: {"type", "help",
    "samples"}}`` where ``samples`` is a list of ``(sample_name,
    labels_dict, value)``.

    Histogram ``_bucket``/``_sum``/``_count`` samples attach to their
    declared family; samples with no ``# TYPE`` line become their own
    family with ``type=None`` (the nv_* compatibility gauges).  This
    is the parser the fleet aggregator and the chaos soaks share —
    tests pin the format with their own independent parser."""
    families = {}

    def fam(name):
        return families.setdefault(
            name, {"type": None, "help": None, "samples": []})

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            name, _, help_text = line[len("# HELP "):].partition(" ")
            fam(name)["help"] = help_text
            continue
        if line.startswith("# TYPE "):
            name, _, kind = line[len("# TYPE "):].partition(" ")
            fam(name)["type"] = kind.strip()
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name = m.group("name")
        labels = {
            k: _unescape_label(v)
            for k, v in _LABEL_RE.findall(m.group("labels") or "")
        }
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        family = name
        for suffix in ("_bucket", "_sum", "_count"):
            stripped = name[: -len(suffix)] if name.endswith(suffix) else None
            if stripped and stripped in families:
                family = stripped
                break
        fam(family)["samples"].append((name, labels, value))
    return families
