"""Core serving runtime: model registry, inference execution, shared-memory
registries, statistics.

Protocol-facing frontends live in ``tpuserver.http_frontend`` /
``tpuserver.grpc_frontend``; this module is transport-agnostic and works on
numpy/jax arrays.
"""

import base64
import mmap
import os
import threading
import time

import numpy as np

from tpuserver import faults
from tpuserver import scheduler as _scheduler
from tpuserver._clock import wall_clock_ms
from tpuserver.metrics import MetricsRegistry
from tpuserver.errors import (  # noqa: F401 — re-exported: the public
    # names every frontend/client/test imports from tpuserver.core
    DeadlineExceeded,
    KvExportConflict,
    KvExportNotFound,
    Overloaded,
    ServerError,
    ShmRegionInUse,
    ShuttingDown,
    SlotQuarantined,
    UnknownGeneration,
)
from tritonclient.utils import (
    deserialize_bytes_tensor,
    serialize_byte_tensor,
    serialized_byte_size,
    triton_to_np_dtype,
)

SERVER_NAME = "tpu-triton-server"
SERVER_VERSION = "0.1.0"
SERVER_EXTENSIONS = [
    "classification",
    "sequence",
    "model_repository",
    "model_repository(unload_dependents)",
    "schedule_policy",
    "model_configuration",
    "system_shared_memory",
    "cuda_shared_memory",
    "xla_shared_memory",
    "binary_tensor_data",
    "parameters",
    "statistics",
    "trace",
    "logging",
]


class TensorSpec:
    """Declared input/output tensor: name, wire datatype, dims (-1 dynamic)."""

    def __init__(self, name, datatype, shape):
        self.name = name
        self.datatype = datatype
        self.shape = list(shape)

    def as_metadata(self):
        return {
            "name": self.name,
            "datatype": self.datatype,
            "shape": list(self.shape),
        }


class RequestedOutput:
    """Server-side view of one requested output and its delivery options."""

    def __init__(self, name, binary_data=True, class_count=0,
                 shm_region=None, shm_byte_size=0, shm_offset=0):
        self.name = name
        self.binary_data = binary_data
        self.class_count = class_count
        self.shm_region = shm_region
        self.shm_byte_size = shm_byte_size
        self.shm_offset = shm_offset


class InferRequest:
    """Transport-agnostic inference request."""

    def __init__(self, model_name, model_version="", request_id="",
                 inputs=None, requested_outputs=None, parameters=None):
        self.model_name = model_name
        self.model_version = model_version
        self.id = request_id
        self.inputs = inputs or {}  # name -> np.ndarray (BYTES as np.object_)
        self.requested_outputs = requested_outputs  # list[RequestedOutput]|None
        self.parameters = parameters or {}
        # shm regions the frontend resolved inputs from: a decoupled
        # model pins them for the stream's lifetime, so unregistering
        # the region backing a live prompt view is a typed 409
        self.shm_input_regions = ()
        # monotonic deadline: stamped by the gRPC frontend (context
        # deadline) and/or resolved from the 'timeout' parameter in
        # InferenceServer._resolve_deadline
        self.deadline = None

    @property
    def sequence_id(self):
        return self.parameters.get("sequence_id", 0)

    @property
    def sequence_start(self):
        return bool(self.parameters.get("sequence_start", False))

    @property
    def sequence_end(self):
        return bool(self.parameters.get("sequence_end", False))


class InferResponse:
    """Transport-agnostic inference response."""

    def __init__(self, model_name, model_version, request_id, outputs,
                 parameters=None):
        self.model_name = model_name
        self.model_version = model_version
        self.id = request_id
        # list of (TensorSpec-like dict name/datatype/shape, np.ndarray|None,
        #          delivery dict) — array None when delivered via shm
        self.outputs = outputs
        self.parameters = parameters or {}


#: Reserved key a decoupled model may include in a yielded output dict
#: to attach per-response parameters (e.g. the generation id and token
#: sequence number resumable streams carry on the wire); popped before
#: the dict is interpreted as output tensors.
RESPONSE_PARAMS_KEY = "__response_parameters__"


class Model:
    """Base model: subclasses define specs and ``execute``.

    ``execute(inputs, request)`` returns ``dict name -> np.ndarray``.
    Decoupled models instead implement ``execute_stream`` yielding such dicts
    (possibly zero or many — the decoupled contract).
    Sequence models implement ``execute_sequence(inputs, state, request)``
    returning ``(outputs, new_state)``.
    """

    name = "model"
    platform = "jax"
    backend = "jax"
    max_batch_size = 0
    inputs = ()
    outputs = ()
    decoupled = False
    sequence = False
    ensemble_steps = None  # list of dicts for ensemble models
    labels = None  # name -> list[str] classification labels
    version = "1"
    # server-side dynamic batching (role of the reference server's
    # dynamic_batching model-config block): concurrent single requests
    # are coalesced into one batched ``execute`` call.  On TPU one
    # [N, ...] dispatch keeps the MXU fed and amortizes the
    # host<->device round trip N ways where N serialized [1, ...]
    # dispatches each pay it in full.
    dynamic_batching = False
    max_queue_delay_us = 2000
    # allowed padded batch sizes (ascending); None = powers of two up to
    # max_batch_size.  Fewer buckets = fewer compiled executables —
    # each distinct batch shape is a separate XLA compile, minutes each
    # for conv nets on a tunneled chip.
    batch_buckets = None
    # parallel executor count (role of the reference server's
    # instance_group count): >1 lets batch executions overlap, hiding
    # the host<->device sync round trip of one batch behind the compute
    # of the next — essential when the chip is behind a ~100 ms tunnel.
    instance_count = 1

    def config_dict(self):
        cfg = {
            "name": self.name,
            "platform": self.platform,
            "backend": self.backend,
            "max_batch_size": self.max_batch_size,
            "input": [
                {
                    "name": t.name,
                    "data_type": "TYPE_" + t.datatype,
                    "dims": list(t.shape),
                }
                for t in self.inputs
            ],
            "output": [
                {
                    "name": t.name,
                    "data_type": "TYPE_" + t.datatype,
                    "dims": list(t.shape),
                }
                for t in self.outputs
            ],
            "instance_group": [{
                "name": self.name + "_0",
                "kind": "KIND_CPU"
                if getattr(self, "device_kind", "tpu") == "cpu"
                else "KIND_TPU",
                "count": self.instance_count,
            }],
            "version_policy": {"latest": {"num_versions": 1}},
        }
        if self.decoupled:
            cfg["model_transaction_policy"] = {"decoupled": True}
        if self.dynamic_batching and self.max_batch_size > 1:
            cfg["dynamic_batching"] = {
                "preferred_batch_size": [self.max_batch_size],
                "max_queue_delay_microseconds": self.max_queue_delay_us,
            }
        if self.sequence:
            cfg["sequence_batching"] = {
                "max_sequence_idle_microseconds": 60000000,
                "control_input": [
                    {"name": "START",
                     "control": [{"kind": "CONTROL_SEQUENCE_START",
                                  "int32_false_true": [0, 1]}]},
                    {"name": "END",
                     "control": [{"kind": "CONTROL_SEQUENCE_END",
                                  "int32_false_true": [0, 1]}]},
                ],
            }
        if self.ensemble_steps is not None:
            cfg["platform"] = "ensemble"
            cfg["ensemble_scheduling"] = {"step": self.ensemble_steps}
        return cfg

    def metadata_dict(self):
        return {
            "name": self.name,
            "versions": [self.version],
            "platform": self.platform,
            "inputs": [t.as_metadata() for t in self.inputs],
            "outputs": [t.as_metadata() for t in self.outputs],
        }

    def execute(self, inputs, request):
        raise NotImplementedError

    def execute_stream(self, inputs, request):
        raise NotImplementedError

    def execute_sequence(self, inputs, state, request):
        raise NotImplementedError

    def warmup(self):
        """Trigger compilation with representative shapes (optional)."""


class JaxModel(Model):
    """A model whose compute is a jitted JAX callable.

    ``fn(**inputs) -> dict`` runs under ``jax.jit`` with static shapes; host
    arrays are pushed with ``device_put`` and results fetched once.  Direct
    ``jax.Array`` inputs (the in-process XLA-shm fast path) skip the host
    push entirely.

    ``device_kind`` picks the execution backend: ``"tpu"`` (default —
    whatever jax's default platform is) for real networks, ``"cpu"`` for
    trivial/control models where a per-request host<->HBM round trip would
    cost orders of magnitude more than the compute (the analogue of the
    reference's instance_group KIND_CPU).
    """

    device_kind = "tpu"

    def __init__(self):
        self._jitted = None
        self._device = None
        self._lock = threading.Lock()

    def jax_fn(self, **kwargs):
        raise NotImplementedError

    def prepare(self):
        """One-time eager setup (e.g. parameter initialization), run
        OUTSIDE any jit trace.  Lazily creating params inside the traced
        ``jax_fn`` would store tracers of that trace in model state
        (jitted helpers like jax.random.normal inline into an active
        trace), corrupting every later re-trace."""

    def _get_jitted(self):
        if self._jitted is None:
            with self._lock:
                if self._jitted is None:
                    import jax

                    if self.device_kind == "cpu":
                        try:
                            self._device = jax.devices("cpu")[0]
                        except RuntimeError:
                            self._device = None
                    self._jitted = jax.jit(self.jax_fn)
        return self._jitted

    def execute(self, inputs, request):
        import jax

        fn = self._get_jitted()
        self.prepare()
        dev_inputs = {}
        for name, arr in inputs.items():
            if isinstance(arr, jax.Array) and self._device is None:
                dev_inputs[name] = arr  # zero-copy: stays in HBM
            elif self._device is not None:
                # cpu-kind model: move everything (including device-resident
                # shm arrays) to the host backend — jit rejects inputs
                # committed to different platforms.
                dev_inputs[name] = jax.device_put(arr, self._device)
            else:
                dev_inputs[name] = jax.device_put(arr)
        out = fn(**dev_inputs)
        # Outputs stay as device arrays: the response builder converts
        # (= synchronizes) only when a tensor actually leaves in-band,
        # so XLA-shm-delivered outputs never block on the device — on a
        # remote chip every sync costs a full tunnel round trip, and the
        # zero-sync path is what lets dispatches pipeline.
        return dict(out)


class _SystemShmRegion:
    def __init__(self, name, key, offset, byte_size):
        self.name = name
        self.key = key
        self.offset = offset
        self.byte_size = byte_size
        path = "/dev/shm" + key if key.startswith("/") else "/dev/shm/" + key
        self._fd = os.open(path, os.O_RDWR)
        self._map = mmap.mmap(self._fd, offset + byte_size)

    def read(self, offset, nbytes):
        start = self.offset + offset
        return bytes(self._map[start : start + nbytes])

    def write(self, offset, data):
        start = self.offset + offset
        self._map[start : start + len(data)] = data

    def close(self):
        try:
            self._map.close()
        finally:
            os.close(self._fd)


class _XlaShmRegion:
    """Server-side view of a registered XLA/TPU shared-memory region.

    The raw handle (see tritonclient.utils.xla_shared_memory) names both a
    host staging window (POSIX shm) and, when client and server share a
    process, an in-process buffer registry slot holding live ``jax.Array``s —
    the zero-host-copy fast path.
    """

    def __init__(self, name, raw_handle, device_ordinal, byte_size):
        from tritonclient.utils import xla_shared_memory as xshm

        self.name = name
        self.device_ordinal = device_ordinal
        self.byte_size = byte_size
        self.handle = xshm.attach_from_raw_handle(raw_handle)

    def read(self, offset, nbytes):
        return self.handle.read_bytes(offset, nbytes)

    def write(self, offset, data):
        self.handle.write_bytes(offset, data)

    def get_device_array(self, offset, datatype, shape):
        """Device-resident ``jax.Array`` parked at ``offset``, or None.

        Only live in-process segments qualify (the zero-copy fast path).
        Cross-process attaches hold data in the host staging window; for
        those, returning None lets the caller read host bytes — a jitted
        model will device_put once itself, and numpy models skip the
        device round-trip entirely (eager device_put here would cost two
        transfers per request)."""
        seg = self.handle.get_jax_segment(offset)
        if seg is None:
            return None
        if list(seg.shape) != list(shape):
            seg = seg.reshape(shape)
        return seg

    def put_device_array(self, offset, array):
        return self.handle.put_jax(offset, array)

    def close(self):
        self.handle.detach()


class _BatchSlot:
    """One queued request inside the dynamic batcher."""

    __slots__ = ("inputs", "rows", "event", "outputs", "error",
                 "enqueue_ns", "queue_ns")

    def __init__(self, inputs, rows):
        self.inputs = inputs
        self.rows = rows
        self.event = threading.Event()
        self.outputs = None
        self.error = None
        # KServe-style queue accounting: time from enqueue to the moment
        # a worker starts executing the batch this slot landed in
        self.enqueue_ns = time.monotonic_ns()
        self.queue_ns = 0


class _DynamicBatcher:
    """Coalesces concurrent requests for one model into batched calls.

    Role of the reference server's dynamic batcher (model_config
    ``dynamic_batching``; observable to perf_analyzer as super-linear
    throughput under concurrency).  A worker thread drains a queue:
    the first waiting request opens a window of
    ``model.max_queue_delay_us``; every compatible request (same input
    names, dtypes and trailing dims) that arrives inside it is stacked
    along the batch axis, executed as ONE device call, and the outputs
    are split back per request.  Requests left over (incompatible
    signature or window overflow) seed the next batch, so nothing
    starves.
    """

    def __init__(self, model):
        self._model = model
        self._cond = threading.Condition()
        self._queue = []   # of _BatchSlot  # guarded-by: _cond
        self._stop = False  # guarded-by: _cond
        self._threads = [
            threading.Thread(
                target=self._run,
                name="batcher-{}-{}".format(model.name, i),
                daemon=True,
            )
            for i in range(max(1, model.instance_count))
        ]
        for t in self._threads:
            t.start()

    @staticmethod
    def _signature(inputs):
        return tuple(
            sorted(
                (name, arr.dtype.str, arr.shape[1:])
                for name, arr in inputs.items()
            )
        )

    def submit(self, inputs, rows):
        """Queue one request's inputs; blocks until its batch executes.

        Returns ``(outputs, queue_ns)`` — the request's slice of the
        batched outputs plus the nanoseconds this request waited in the
        batching window before execution started (the KServe ``queue``
        stat bucket; raises the batch's error if execution failed)."""
        slot = _BatchSlot(inputs, rows)
        with self._cond:
            if self._stop:
                raise ServerError(
                    "model '{}' is unloading".format(self._model.name)
                )
            self._queue.append(slot)
            self._cond.notify_all()
        slot.event.wait()
        if slot.error is not None:
            raise slot.error
        return slot.outputs, slot.queue_ns

    def stop(self):
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout=5)
        # snapshot under the lock: a worker that outlived the join may
        # still rebind the queue in _take_batch_locked; slots it has
        # taken will complete normally, only still-queued slots get
        # errored
        with self._cond:
            pending, self._queue = self._queue, []
        for slot in pending:
            slot.error = ServerError(
                "model '{}' is unloading".format(self._model.name)
            )
            slot.event.set()

    def _take_batch_locked(self):
        """Collect one compatible batch.  Called with ``_cond`` held
        (the ``_locked`` suffix is the convention tpulint R1 keys on)."""
        max_rows = self._model.max_batch_size
        sig = self._signature(self._queue[0].inputs)
        batch, rest, rows = [], [], 0
        for slot in self._queue:
            if (
                rows + slot.rows <= max_rows
                and self._signature(slot.inputs) == sig
            ):
                batch.append(slot)
                rows += slot.rows
            else:
                rest.append(slot)
        if not batch:
            # oversized single request: run it alone, the model's own
            # shape validation decides its fate
            batch, rest = [rest[0]], rest[1:]
            rows = batch[0].rows
        self._queue = rest
        return batch, rows

    def _run(self):
        delay_s = self._model.max_queue_delay_us / 1e6
        while True:
            with self._cond:
                while not self._queue and not self._stop:
                    self._cond.wait()
                if self._stop:
                    return
                # batching window: wait for companions until the delay
                # elapses or a full preferred batch is queued
                deadline = time.monotonic() + delay_s
                while (
                    sum(s.rows for s in self._queue)
                    < self._model.max_batch_size
                    and not self._stop
                ):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
                if self._stop:
                    return
                if not self._queue:
                    # a sibling instance thread drained the queue while
                    # this one sat in its batching window
                    continue
                batch, rows = self._take_batch_locked()
            self._execute(batch, rows)

    def _bucket(self, rows, max_rows):
        """Smallest allowed padded batch >= rows: every jit model
        compiles one executable per distinct batch shape, so padding
        the batch axis to a few fixed buckets bounds the compile set
        (model.batch_buckets, default powers of two up to max_batch)
        instead of one compile per concurrency level."""
        buckets = self._model.batch_buckets
        if buckets:
            for b in buckets:
                if b >= rows:
                    return b
            return max(buckets[-1], rows)
        b = 1
        while b < rows:
            b <<= 1
        return min(b, max(max_rows, rows))

    def _stack(self, batch, rows, padded):
        """Build the batched input dict.

        Host (numpy) parts are stacked host-side into one bucket-shaped
        array — the model's single device_put moves the whole batch in
        one transfer, and the compiled-shape set stays exactly the
        bucket set.  Device-resident parts (the XLA-shm fast path) are
        concatenated on device instead, so they never round-trip through
        the host; the padding rows replicate row 0.
        """
        stacked = {}
        for name in batch[0].inputs:
            raw_parts = [s.inputs[name] for s in batch]
            if all(isinstance(p, np.ndarray) for p in raw_parts):
                parts = raw_parts
                if padded > rows:
                    parts = parts + [
                        np.repeat(parts[0][:1], padded - rows, axis=0)
                    ]
                stacked[name] = (
                    np.concatenate(parts, axis=0)
                    if len(parts) > 1
                    else parts[0]
                )
            else:
                import jax
                import jax.numpy as jnp

                parts = [
                    p if isinstance(p, jax.Array) else jax.device_put(p)
                    for p in raw_parts
                ]
                x = (
                    jnp.concatenate(parts, axis=0)
                    if len(parts) > 1
                    else parts[0]
                )
                if padded > rows:
                    x = jnp.concatenate(
                        [x, jnp.repeat(x[:1], padded - rows, axis=0)],
                        axis=0,
                    )
                stacked[name] = x
        return stacked

    def _execute(self, batch, rows):
        t_start = time.monotonic_ns()
        for slot in batch:
            slot.queue_ns = max(0, t_start - slot.enqueue_ns)
        try:
            padded = self._bucket(rows, self._model.max_batch_size)
            stacked = self._stack(batch, rows, padded)
            outputs = self._model.execute(stacked, None)
            if len(batch) > 1:
                # materialize device outputs ONCE for the whole batch:
                # splitting into per-slot device slices would make each
                # response pay its own device sync (a full tunnel round
                # trip apiece) for the same bytes
                outputs = {
                    k: v if isinstance(v, np.ndarray) else np.asarray(v)
                    for k, v in outputs.items()
                }
            # A max_batch_size>0 model's declared outputs always carry
            # the batch dim (Triton config semantics), so split them by
            # declaration — including ones the model returned un-padded
            # (shape[0] == rows).  Undeclared extras have no spec to
            # consult; they fall back to the padded-shape heuristic so
            # a batch-shaped extra is still split per request (never
            # replicated whole, which would leak other requests' rows).
            declared = {t.name for t in self._model.outputs}
            for name, arr in outputs.items():
                if name not in declared:
                    continue
                ndim = getattr(arr, "ndim", 0)
                if ndim < 1 or arr.shape[0] not in (rows, padded):
                    # a misdeclared un-batched output (e.g. [1000] class
                    # scores for a 3-row batch) must fail loudly — the
                    # declaration-driven split would otherwise slice it
                    # into wrong per-request rows
                    raise ValueError(
                        "declared output '{}' of model '{}' must carry "
                        "the batch dim (shape[0] in ({}, {})), got shape "
                        "{}".format(
                            name, self._model.name, rows, padded,
                            tuple(getattr(arr, "shape", ())),
                        )
                    )
            offset = 0
            for slot in batch:
                slot.outputs = {}
                for name, arr in outputs.items():
                    ndim = getattr(arr, "ndim", 0)
                    batched = ndim >= 1 and (
                        name in declared or arr.shape[0] == padded
                    )
                    if batched and arr.shape[0] >= rows:
                        if len(batch) == 1 and arr.shape[0] == slot.rows:
                            slot.outputs[name] = arr  # no split needed
                        else:
                            slot.outputs[name] = arr[
                                offset : offset + slot.rows
                            ]
                    else:  # non-batched output: replicate
                        slot.outputs[name] = arr
                offset += slot.rows
        except Exception as e:  # noqa: BLE001 — failure fans out per slot
            # each waiting frontend thread raises its own slot.error;
            # handing every slot the same instance would race the
            # interpreter's __traceback__ mutation on concurrent raises.
            # ValueError keeps the 400 the frontends would have mapped it
            # to on the unbatched path; everything else is a server 500.
            code = getattr(
                e, "code", 400 if isinstance(e, ValueError) else 500
            )
            for slot in batch:
                slot.error = ServerError(
                    "batched execution failed for model '{}': {}".format(
                        self._model.name, e
                    ),
                    code=code,
                )
        finally:
            for slot in batch:
                slot.event.set()


class _ModelStats:
    def __init__(self):
        self.lock = threading.Lock()
        self.inference_count = 0     # guarded-by: lock
        self.execution_count = 0     # guarded-by: lock
        # epoch ms, the KServe statistics wire contract — a REPORTING
        # field, stamped through the sanctioned _clock.wall_clock_ms()
        # boundary.  Nothing may do liveness/recency math on it (wall
        # clocks jump; tpulint R3 bans wall-clock reads everywhere
        # else, so a monotonic source must be added if such math ever
        # appears).
        self.last_inference_ms = 0   # guarded-by: lock
        self.success_count = 0       # guarded-by: lock
        self.success_ns = 0          # guarded-by: lock
        self.fail_count = 0          # guarded-by: lock
        self.fail_ns = 0             # guarded-by: lock
        self.queue_ns = 0            # guarded-by: lock
        self.compute_input_ns = 0    # guarded-by: lock
        self.compute_infer_ns = 0    # guarded-by: lock
        self.compute_output_ns = 0   # guarded-by: lock

    def record(self, batch, queue_ns, ci_ns, cf_ns, co_ns, ok=True):
        with self.lock:
            if ok:
                self.inference_count += batch
                self.execution_count += 1
                self.last_inference_ms = wall_clock_ms()
                self.success_count += 1
                self.success_ns += queue_ns + ci_ns + cf_ns + co_ns
                self.queue_ns += queue_ns
                self.compute_input_ns += ci_ns
                self.compute_infer_ns += cf_ns
                self.compute_output_ns += co_ns
            else:
                self.fail_count += 1
                self.fail_ns += queue_ns + ci_ns + cf_ns + co_ns

    def as_dict(self, name, version):
        with self.lock:
            def sd(count, ns):
                return {"count": count, "ns": ns}

            return {
                "name": name,
                "version": version,
                "last_inference": self.last_inference_ms,
                "inference_count": self.inference_count,
                "execution_count": self.execution_count,
                "inference_stats": {
                    "success": sd(self.success_count, self.success_ns),
                    "fail": sd(self.fail_count, self.fail_ns),
                    "queue": sd(self.success_count, self.queue_ns),
                    "compute_input": sd(self.success_count,
                                        self.compute_input_ns),
                    "compute_infer": sd(self.success_count,
                                        self.compute_infer_ns),
                    "compute_output": sd(self.success_count,
                                         self.compute_output_ns),
                    "cache_hit": sd(0, 0),
                    "cache_miss": sd(0, 0),
                },
                "batch_stats": [],
            }


class InferenceServer:
    """The serving core: models, shared memory, statistics, settings.

    Lifecycle: ``starting`` (constructed with ``ready=False``, e.g.
    while warmup compiles run) -> ``ready`` -> ``draining`` (via
    :meth:`drain`/:meth:`begin_drain`) -> ``stopped`` (via
    :meth:`close`).  :meth:`server_ready` reports True only in
    ``ready`` with every model's health check passing, so load
    balancers see drain and watchdog trips, not a constant.

    ``max_inflight`` is the server-wide overload valve: when that many
    requests are executing, further ones are shed with a typed
    :class:`Overloaded` (HTTP 429 + Retry-After) instead of queueing
    without bound behind a saturated device.
    """

    def __init__(self, models=None, max_inflight=None, ready=True,
                 fault_scope=None, role=None, spawn_nonce=None):
        # identifies this replica at shared fault-injection points, so
        # multi-server chaos harnesses can break ONE in-process replica
        # (tpuserver.faults scopes)
        self.fault_scope = fault_scope
        # spawn identity nonce (fleet supervisor adoption): echoed in
        # health_snapshot so a RESTARTED supervisor can prove the
        # process on a recorded port is the exact child it spawned
        # before claiming it (fleetmanifest adoption contract)
        self.spawn_nonce = spawn_nonce
        # disaggregated-serving role ("prefill" | "decode" | None =
        # fused): advertised in health_snapshot so a fleet router can
        # partition its candidate pools by phase without configuration
        self.role = role
        self._models = {}  # name -> Model
        self._ready = {}  # name -> bool
        self._stats = {}  # name -> _ModelStats
        self._lock = threading.Lock()
        # lifecycle state machine; reads go through server_state() so
        # probes never see a torn transition
        self._state = "ready" if ready else "starting"  # guarded-by: _inflight_cond
        self._max_inflight = max_inflight  # guarded-by: _inflight_cond
        self._inflight = 0  # guarded-by: _inflight_cond
        self._inflight_cond = threading.Condition()
        self._system_shm = {}
        self._cuda_shm = {}  # parity only; registration succeeds, no CUDA io
        self._xla_shm = {}
        # region name -> reference count of in-flight generations /
        # token rings holding the region (guarded by _shm_lock):
        # unregister of a pinned region is a typed 409 conflict, never
        # a crash or silent corruption under the zero-copy data plane
        self._shm_pins = {}
        self._shm_lock = threading.Lock()
        # generation id -> (region name, parked position, shape, wire
        # dtype): the server-owned XLA-shm KV exports a parked
        # generation leaves behind so a same-host resume re-scatters
        # instead of re-prefilling  # guarded-by: _shm_lock
        self._kv_exports = {}
        # generation ids whose export descriptor was already handed out:
        # the disaggregated transfer contract is one-shot (exactly one
        # decode replica re-scatters a prefill leg), so a second fetch
        # is a typed 409, not a silent double-attach  # guarded-by: _shm_lock
        self._kv_export_claims = set()
        self._batchers = {}  # name -> _DynamicBatcher (lazily created;
        # double-checked locking — deliberately unannotated, see
        # docs/static_analysis.md R1)
        self._closed = False  # guarded-by: _lock
        # attached frontends; last detach closes  # guarded-by: _lock
        self._frontends = 0
        self._sequence_state = {}  # (model, seq_id) -> (state, touched)
        self._last_sequence_sweep = 0.0
        self._trace_settings = {
            "trace_file": [""],
            "trace_level": ["OFF"],
            "trace_rate": ["1000"],
            "trace_count": ["-1"],
            "log_frequency": ["0"],
        }
        self._log_settings = {
            "log_file": "",
            "log_info": True,
            "log_warning": True,
            "log_error": True,
            "log_verbose_level": 0,
            "log_format": "default",
        }
        # the replica's telemetry plane (docs/observability.md):
        # owned per-verb instruments plus a scrape-time collector over
        # every model's scheduler counters — the scheduler stays the
        # single account of its own events, the registry is a view.
        # Verb children are pre-bound so the per-request hot path
        # costs two lock-free adds, never a family-lock lookup.
        self.metrics = MetricsRegistry()
        requests_family = self.metrics.counter(
            "tpu_requests_total", labelnames=("verb",))
        seconds_family = self.metrics.histogram(
            "tpu_request_seconds", labelnames=("verb",))
        self._metric_errors = self.metrics.counter(
            "tpu_request_errors_total", labelnames=("verb", "code"))
        self._m_infer_count = requests_family.labels(verb="infer")
        self._m_infer_hist = seconds_family.labels(verb="infer")
        self._m_stream_count = requests_family.labels(verb="stream_infer")
        self._m_stream_hist = seconds_family.labels(verb="stream_infer")
        # (verb, code) -> bound counter child; plain-dict cache so the
        # error path never re-pays the family lock
        self._metric_error_children = {}
        # shared-memory data-plane traffic: bytes materialized from /
        # written into registered regions (device-resident zero-copy
        # transfers count their logical tensor size — the bytes that
        # did NOT cross the wire)
        self._m_shm_read = self.metrics.counter(
            "tpu_shm_bytes_read_total").labels()
        self._m_shm_written = self.metrics.counter(
            "tpu_shm_bytes_written_total").labels()
        self.metrics.register_collector(self._collect_metrics)
        self.metrics.register_collector(self._collect_shm_ring)
        for m in models or []:
            self.register_model(m)

    # -- model repository --------------------------------------------------

    def register_model(self, model, ready=True):
        with self._lock:
            self._models[model.name] = model
            self._ready[model.name] = ready
            self._stats.setdefault(model.name, _ModelStats())
        attach = getattr(model, "attach_server", None)
        if attach is not None:
            attach(self)

    def _get_model(self, name, version=""):
        model = self._models.get(name)
        if model is None:
            raise ServerError(
                "Request for unknown model: '{}' is not found".format(name),
                code=404,
            )
        if version not in ("", model.version):
            raise ServerError(
                "Request for unknown model version: '{}' version {}".format(
                    name, version
                ),
                code=404,
            )
        if not self._ready.get(name, False):
            raise ServerError(
                "Model '{}' is not ready".format(name), code=400
            )
        return model

    def requires_stream_order(self, name, version=""):
        """Whether stream requests to this model must execute in arrival
        order: decoupled response bursts are contractual, and sequence
        state depends on step order.

        Continuous-batching decoupled models (``concurrent_decoupled``,
        e.g. the llama scheduler with ``max_slots > 1``) opt OUT of
        per-stream serialization: their whole point is that many
        generations run interleaved on the chip, each response carrying
        its request id so clients demultiplex."""
        model = self._get_model(name, version)
        if model.sequence:
            return True
        if model.decoupled:
            return not getattr(model, "concurrent_decoupled", False)
        return False

    def is_concurrent_decoupled(self, name, version=""):
        """Whether this model runs decoupled requests interleaved (the
        continuous-batching scheduler).  Such requests self-limit via
        the model's slot count, so stream frontends must not cap them
        with their own in-flight bound — a long-lived generation would
        otherwise starve the scheduler of work it has slots for."""
        model = self._models.get(name)
        return bool(
            model is not None
            and model.decoupled
            and getattr(model, "concurrent_decoupled", False)
        )

    def model_ready(self, name, version=""):
        model = self._models.get(name)
        return (
            model is not None
            and version in ("", model.version)
            and self._ready.get(name, False)
            and self.server_state() == "ready"
            and self._model_healthy(model)
        )

    @staticmethod
    def _model_healthy(model):
        """A model may expose ``healthy`` (property or callable) — e.g.
        the continuous-batching scheduler's watchdog; absent means
        healthy."""
        probe = getattr(model, "healthy", None)
        if probe is None:
            return True
        return bool(probe() if callable(probe) else probe)

    # -- lifecycle / readiness ---------------------------------------------

    def server_state(self):
        """``starting`` | ``ready`` | ``draining`` | ``stopped``."""
        with self._inflight_cond:
            return self._state

    def server_ready(self):
        """Real readiness for load balancers: True only when serving
        (not starting/draining/stopped) and every registered model's
        health probe passes (a tripped scheduler watchdog reports
        here)."""
        if self.server_state() != "ready":
            return False
        with self._lock:  # snapshot: register_model mutates under _lock
            models = list(self._models.items())
        for name, model in models:
            if self._ready.get(name, False) and not self._model_healthy(
                model
            ):
                return False
        return True

    def health_snapshot(self):
        """Cheap machine-readable health/load snapshot — the routing
        signal a fleet router's prober polls (`/v2/health/stats`).

        Deliberately NOT the per-model inference-statistics verb: this
        touches only the lifecycle state, the in-flight counter, and
        each model's scheduler counters (one lock hold apiece), so a
        sub-second probe cadence across a fleet costs nothing.  Shape::

            {"state": "ready", "ready": true, "inflight": 3,
             "max_inflight": 64, "pid": 4242, "role": null,
             "models": {"llama_generate": {<DecodeScheduler.stats()>}}}

        ``role`` is the disaggregated-serving phase this replica is
        dedicated to (``"prefill"`` / ``"decode"``, None = fused) — the
        signal a phase-aware router partitions its candidate pools by.

        ``pid`` identifies the serving *process*: a fleet supervisor
        restarting replicas at a stable address can tell a healed
        process from a survivor without tracking anything else.

        ``spawn_nonce`` (when the spawner passed one) closes the
        adoption loop: pid + start-time token prove "a process", the
        echoed nonce proves "MY process" — a foreign server squatting
        the recorded port can never be claimed by a restarted
        supervisor.

        ``models`` maps each registered model to its scheduler stats
        dict (``None`` for models with no scheduler, or before first
        use) — ``tripped``/``restarts``/``replay_entries`` and the
        ``live_streams``/``pending`` vs ``max_slots``/``max_pending``
        utilization are the routing and shed signals."""
        with self._inflight_cond:
            state = self._state
            inflight = self._inflight
            max_inflight = self._max_inflight
        with self._lock:
            items = list(self._models.items())
        models = {}
        for name, model in items:
            stats_fn = getattr(model, "scheduler_stats", None)
            models[name] = stats_fn() if callable(stats_fn) else None
        snap = {
            "state": state,
            "ready": self.server_ready(),
            "inflight": inflight,
            "max_inflight": max_inflight,
            "pid": os.getpid(),
            "role": self.role,
            "models": models,
        }
        if self.spawn_nonce is not None:
            snap["spawn_nonce"] = self.spawn_nonce
        return snap

    # -- telemetry ---------------------------------------------------------

    def _count_error(self, verb, code):
        key = (verb, str(code))
        child = self._metric_error_children.get(key)
        if child is None:
            child = self._metric_errors.labels(verb=verb, code=key[1])
            self._metric_error_children[key] = child
        child.inc()

    def _collect_metrics(self):
        """Scrape-time collector: the in-flight gauge plus every
        scheduler-backed model's counters, read straight from
        ``scheduler_stats()`` — one source of truth, no double
        accounting (test-pinned in tests/test_metrics.py)."""
        with self._inflight_cond:
            inflight = self._inflight
        families = [("tpu_inflight_requests", [({}, inflight)])]
        families.append((
            "tpu_shm_regions",
            [({"kind": "system"}, len(self._system_shm)),
             ({"kind": "cuda"}, len(self._cuda_shm)),
             ({"kind": "xla"}, len(self._xla_shm))],
        ))
        with self._lock:
            items = list(self._models.items())
        per_family = {
            "tpu_scheduler_admissions_total": "admitted",
            "tpu_scheduler_tokens_total": "tokens",
            "tpu_scheduler_restarts_total": "restarts",
            "tpu_scheduler_quarantined_total": "quarantined",
            "tpu_scheduler_replay_hits_total": "replay_hits",
            "tpu_scheduler_live_streams": "live_streams",
            "tpu_scheduler_pending": "pending",
            # adaptive queue shedding (tail-latency defense): sheds by
            # the sojourn controller + whether it is shedding NOW
            # (bool coerces to the 0/1 gauge)
            "tpu_scheduler_codel_sheds_total": "codel_sheds",
            "tpu_scheduler_codel_shedding": "codel_shedding",
            # paged KV + radix prefix cache (PR 11): the counters
            # perfanalyzer's hit-rate column window-diffs, and the
            # page-utilization gauges
            "tpu_prefix_cache_hits_total": "prefix_hits",
            "tpu_prefix_cache_misses_total": "prefix_misses",
            "tpu_prefix_cache_evictions_total": "prefix_evictions",
            "tpu_kv_pages_total": "pages_total",
            "tpu_kv_pages_free": "pages_free",
            "tpu_kv_pages_cached": "pages_cached",
            # speculative decoding (ISSUE 19): proposal/acceptance
            # counters perfanalyzer's accept-rate columns window-diff,
            # plus the lifetime accepted-per-step gauge
            "tpu_spec_tokens_proposed_total": "spec_proposed",
            "tpu_spec_tokens_accepted_total": "spec_accepted",
            "tpu_spec_rollbacks_total": "spec_rollbacks",
            "tpu_spec_steps_total": "spec_steps",
            "tpu_spec_accept_per_step": "spec_accept_per_step",
        }
        # the one non-integral family: a mean, exposed as-is (every
        # other stats value is a count or 0/1 flag)
        float_families = {"tpu_spec_accept_per_step"}
        samples = {name: [] for name in per_family}
        for model_name, model in items:
            stats_fn = getattr(model, "scheduler_stats", None)
            stats = stats_fn() if callable(stats_fn) else None
            if not isinstance(stats, dict):
                continue
            for fam_name, key in per_family.items():
                val = stats.get(key) or 0
                samples[fam_name].append(
                    ({"model": model_name},
                     float(val) if fam_name in float_families
                     else int(val)))
        families.extend(
            (name, rows) for name, rows in samples.items() if rows)
        return families

    @staticmethod
    def _collect_shm_ring():
        """Scrape-time view of the process-wide seqlock torn-read
        counter (tpuserver.shm_ring) — readers are client-side code
        with no server handle, so the module counter is the single
        account and this is its exposition."""
        from tpuserver import shm_ring

        return [("tpu_shm_ring_torn_total", [({}, shm_ring.torn_total())])]

    def metrics_text(self):
        """The replica's full ``/metrics`` exposition: the ``nv_*``
        compatibility gauges (what the reference server publishes on
        :8002 and perf_analyzer ``--collect-metrics`` scrapes,
        metrics_manager.h:44-91) followed by the ``tpu_*`` registry.
        One snapshot for both transports: the HTTP frontend serves it
        at ``GET /metrics`` and the gRPC frontend via the
        ``ServerMetrics`` unary."""
        lines = []
        rss_bytes = None
        try:
            # current RSS (ru_maxrss is the PEAK, and its unit is
            # platform-dependent; /proc is authoritative on Linux)
            with open("/proc/self/statm") as f:
                rss_bytes = int(f.read().split()[1]) * os.sysconf(
                    "SC_PAGE_SIZE")
        except Exception:
            try:
                import resource
                import sys

                peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
                # Linux reports KB, macOS bytes; label it as the peak
                # it is rather than mislabeling it current
                rss_bytes = peak * (1 if sys.platform == "darwin" else 1024)
            except Exception:
                pass
        if rss_bytes is not None:
            lines.append(
                "# HELP nv_cpu_memory_used_bytes Server RSS.\n"
                "# TYPE nv_cpu_memory_used_bytes gauge\n"
                "nv_cpu_memory_used_bytes {}".format(rss_bytes))
        try:
            import jax

            devices = [
                d for d in jax.devices() if d.platform != "cpu"
            ]
            for i, dev in enumerate(devices):
                stats = {}
                try:
                    stats = dev.memory_stats() or {}
                except Exception:
                    pass
                used = stats.get("bytes_in_use", 0)
                total = stats.get("bytes_limit", 0)
                label = '{{tpu="{}"}}'.format(i)
                lines.append(
                    "nv_gpu_memory_used_bytes{} {}".format(label, used))
                lines.append(
                    "nv_gpu_memory_total_bytes{} {}".format(label, total))
                if total:
                    # a memory fraction, NOT compute duty-cycle — keep it
                    # out of nv_gpu_utilization (whose nv_* semantics,
                    # and perf_analyzer's averaging, mean busy-percent)
                    lines.append(
                        "nv_gpu_memory_utilization{} {}".format(
                            label, used / total))
        except Exception:
            pass
        for stat in self.model_statistics()["model_stats"]:
            label = '{{model="{}"}}'.format(stat["name"])
            lines.append(
                "nv_inference_count{} {}".format(
                    label, stat["inference_count"]))
            lines.append(
                "nv_inference_exec_count{} {}".format(
                    label, stat["execution_count"]))
        return ("\n".join(lines) + "\n" if lines else "") \
            + self.metrics.render()

    def mark_ready(self):
        """Flip a ``starting`` server to ``ready`` (after warmup), or
        cancel an in-progress ``begin_drain()`` (an ops undrain: the
        replica rejoins the fleet and readiness probes flip back).  A
        ``stopped`` server stays stopped — its workers are gone; only
        ``attach_frontend`` re-opens one."""
        with self._inflight_cond:
            if self._state in ("starting", "draining"):
                self._state = "ready"
                # wake a drain() waiting on inflight==0 so it observes
                # the cancellation instead of closing a serving server
                self._inflight_cond.notify_all()

    def set_max_inflight(self, max_inflight):
        """Adjust the server-wide in-flight cap at runtime (None lifts
        it); an ops valve, also what overload tests flip."""
        with self._inflight_cond:
            self._max_inflight = max_inflight
            self._inflight_cond.notify_all()

    def _enter_inflight(self):
        with self._inflight_cond:
            if self._state != "ready":
                reason = {
                    "starting": "starting and not yet ready",
                    "draining": "draining",
                }.get(self._state, "shut down")
                raise ShuttingDown(
                    "server is {}; not accepting new requests".format(
                        reason
                    )
                )
            if (
                self._max_inflight is not None
                and self._inflight >= self._max_inflight
            ):
                raise Overloaded(
                    "server is at its in-flight request cap ({}); "
                    "retry later".format(self._max_inflight)
                )
            self._inflight += 1

    def _exit_inflight(self):
        with self._inflight_cond:
            self._inflight -= 1
            # the only waiter is drain()'s inflight==0 loop, and it can
            # only be waiting after begin_drain() flipped the state (a
            # flip this exit cannot miss: both run under the cond) — a
            # ready-state exit pays no wakeup syscall on the hot path
            if self._state != "ready":
                self._inflight_cond.notify_all()

    def inflight_count(self):
        with self._inflight_cond:
            return self._inflight

    def begin_drain(self):
        """Stop admission and flip readiness; in-flight work continues.
        The first half of :meth:`drain`, split out so probes can observe
        the draining state."""
        with self._inflight_cond:
            if self._state != "stopped":
                self._state = "draining"

    def drain(self, timeout=30.0):
        """Graceful shutdown: stop admission (new requests get a typed
        503), let in-flight requests — including scheduler-backed
        generations — finish within ``timeout`` seconds, then close,
        deterministically failing whatever remains.

        A concurrent :meth:`mark_ready` (undrain) aborts the drain:
        once the server is admitting again, running ``close()`` would
        hard-kill the just-admitted requests.  Undrain is only safe
        BEFORE the wait completes — cancel early or not at all."""
        self.begin_drain()
        deadline = time.monotonic() + timeout
        # model-owned schedulers drain first: their in-flight
        # generations are the long-lived work the deadline budgets for.
        # Per-model guard: one failing drainer must not abort the whole
        # graceful shutdown (the server would be stuck 'draining' with
        # close() never reached)
        for model in list(self._models.values()):
            drainer = getattr(model, "drain", None)
            if callable(drainer):
                try:
                    drainer(max(0.0, deadline - time.monotonic()))
                except Exception:  # noqa: BLE001 — close() must run
                    pass
        with self._inflight_cond:
            while self._inflight > 0 and self._state == "draining":
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._inflight_cond.wait(remaining)
            if self._state == "ready":
                return  # undrained mid-wait: the server is serving again
        self.close()

    def load_model(self, name):
        if name not in self._models:
            raise ServerError(
                "failed to load '{}', no such model".format(name), code=400
            )
        self._ready[name] = True

    def unload_model(self, name, unload_dependents=False):
        if name not in self._models:
            raise ServerError(
                "failed to unload '{}', no such model".format(name), code=400
            )
        self._ready[name] = False
        if unload_dependents:
            model = self._models[name]
            for step in model.ensemble_steps or []:
                if step["model_name"] in self._models:
                    self._ready[step["model_name"]] = False

    def repository_index(self, ready_only=False):
        out = []
        for name, model in sorted(self._models.items()):
            ready = self._ready.get(name, False)
            if ready_only and not ready:
                continue
            out.append(
                {
                    "name": name,
                    "version": model.version,
                    "state": "READY" if ready else "UNAVAILABLE",
                    "reason": "",
                }
            )
        return out

    # -- metadata ----------------------------------------------------------

    def server_metadata(self):
        return {
            "name": SERVER_NAME,
            "version": SERVER_VERSION,
            "extensions": list(SERVER_EXTENSIONS),
        }

    def model_metadata(self, name, version=""):
        return self._get_model(name, version).metadata_dict()

    def model_config(self, name, version=""):
        return self._get_model(name, version).config_dict()

    def model_statistics(self, name="", version=""):
        out = []
        for mname, model in sorted(self._models.items()):
            if name and mname != name:
                continue
            out.append(self._stats[mname].as_dict(mname, model.version))
        if name and not out:
            raise ServerError(
                "Request for unknown model: '{}' is not found".format(name),
                code=404,
            )
        return {"model_stats": out}

    # -- settings ----------------------------------------------------------

    def get_trace_settings(self, model_name=None):
        return {"settings": dict(self._trace_settings)}

    def update_trace_settings(self, model_name=None, settings=None):
        for key, val in (settings or {}).items():
            if val is None:
                continue
            self._trace_settings[key] = (
                [str(v) for v in val] if isinstance(val, list) else [str(val)]
            )
        return self.get_trace_settings(model_name)

    def get_log_settings(self):
        return dict(self._log_settings)

    def update_log_settings(self, settings):
        for key, val in (settings or {}).items():
            if key not in self._log_settings:
                raise ServerError("unknown log setting '{}'".format(key))
            self._log_settings[key] = val
        return self.get_log_settings()

    # -- shared memory -----------------------------------------------------

    def register_system_shm(self, name, key, offset, byte_size):
        if name in self._system_shm:
            raise ServerError(
                "shared memory region '{}' already in manager".format(name)
            )
        try:
            region = _SystemShmRegion(name, key, offset, byte_size)
        except OSError as e:
            raise ServerError(
                "unable to open shared memory region '{}': {}".format(name, e)
            )
        with self._shm_lock:  # publish atomically vs pin/unregister
            if name in self._system_shm:
                region.close()
                raise ServerError(
                    "shared memory region '{}' already in "
                    "manager".format(name)
                )
            self._system_shm[name] = region

    def unregister_system_shm(self, name=""):
        # pin check and registry pop are ONE atomic step under
        # _shm_lock: a pin taken concurrently (a generation starting)
        # either lands before the pop — and the unregister conflicts —
        # or after — and finds the region gone, a typed 400.  The
        # close itself (syscalls) runs outside the lock.
        with self._shm_lock:
            if name:
                self._check_unpinned_locked(name)
                regions = [self._system_shm.pop(name, None)]
            else:
                for rname in self._system_shm:
                    self._check_unpinned_locked(rname)
                regions = list(self._system_shm.values())
                self._system_shm.clear()
        for region in regions:
            if region is not None:
                region.close()

    def system_shm_status(self, name=""):
        regions = {}
        for rname, r in self._system_shm.items():
            if name and rname != name:
                continue
            regions[rname] = {
                "name": rname,
                "key": r.key,
                "offset": r.offset,
                "byte_size": r.byte_size,
            }
        return regions

    def register_cuda_shm(self, name, raw_handle, device_id, byte_size):
        raise ServerError(
            "failed to register CUDA shared memory region '{}': no CUDA "
            "devices on a TPU host (use xla shared memory)".format(name)
        )

    def unregister_cuda_shm(self, name=""):
        self._cuda_shm.clear()

    def cuda_shm_status(self, name=""):
        return {}

    def register_xla_shm(self, name, raw_handle, device_ordinal, byte_size):
        if name in self._xla_shm:
            raise ServerError(
                "shared memory region '{}' already in manager".format(name)
            )
        try:
            region = _XlaShmRegion(
                name, raw_handle, device_ordinal, byte_size
            )
        except Exception as e:
            raise ServerError(
                "unable to attach xla shared memory region '{}': {}".format(
                    name, e
                )
            )
        with self._shm_lock:  # publish atomically vs pin/unregister
            if name in self._xla_shm:
                region.close()
                raise ServerError(
                    "shared memory region '{}' already in "
                    "manager".format(name)
                )
            self._xla_shm[name] = region

    def unregister_xla_shm(self, name=""):
        # same atomicity as unregister_system_shm: check + pop under
        # one _shm_lock hold, close/unlink outside it
        with self._shm_lock:
            if name:
                self._check_unpinned_locked(name)
                dropped = [(name, self._xla_shm.pop(name, None))]
            else:
                for rname in self._xla_shm:
                    self._check_unpinned_locked(rname)
                dropped = list(self._xla_shm.items())
                self._xla_shm.clear()
            for rname, _ in dropped:
                self._drop_export_entry_locked(rname)
        for _, region in dropped:
            if region is not None:
                region.close()
                self._destroy_owned(region)

    def xla_shm_status(self, name=""):
        regions = {}
        for rname, r in self._xla_shm.items():
            if name and rname != name:
                continue
            regions[rname] = {
                "name": rname,
                "device_ordinal": r.device_ordinal,
                "byte_size": r.byte_size,
            }
        return regions

    # -- region pinning (the in-flight-reference contract) -----------------

    def pin_shm_region(self, name):
        """Mark ``name`` as referenced by an in-flight generation or a
        registered token ring.  While pinned, unregister is a typed
        409 :class:`ShmRegionInUse` — never a crash mid-stream or a
        silent write into freed memory.  Raises the usual 400 when the
        region is not registered at all.  Pins nest (one per
        referencing stream); pair every pin with :meth:`unpin_shm_region`."""
        with self._shm_lock:
            self._shm_region(name)  # existence check, typed 400
            self._shm_pins[name] = self._shm_pins.get(name, 0) + 1

    def unpin_shm_region(self, name):
        with self._shm_lock:
            count = self._shm_pins.get(name, 0) - 1
            if count > 0:
                self._shm_pins[name] = count
            else:
                self._shm_pins.pop(name, None)

    def _check_unpinned_locked(self, name):
        """Raise the typed 409 for a pinned region.  Called with
        ``_shm_lock`` held (the unregister paths take it around the
        check AND the registry pop, so a concurrent pin can never land
        between the two)."""
        pins = self._shm_pins.get(name, 0)
        if pins > 0:
            raise ShmRegionInUse(
                "cannot unregister shared memory region '{}': {} "
                "in-flight generation(s) or token ring(s) still "
                "reference it; retry after they finish".format(name, pins)
            )

    # -- server-owned KV exports (park-attach resume) ----------------------

    @staticmethod
    def _kv_export_region_name(generation_id):
        return "kvexport/{}".format(generation_id)

    def export_kv_region(self, generation_id, cache, position):
        """Park a finished-with-for-now generation's gathered KV pages
        (a device-resident ``jax.Array``) as a server-owned XLA-shm
        region keyed by the generation id.  A same-host resume (or a
        restarted frontend over the same core) attaches the region and
        re-scatters it instead of re-prefilling ``prompt + history`` —
        token-identical by construction (greedy decode is
        deterministic; pinned in tests/test_shm_data_plane.py)."""
        from tritonclient.utils import xla_shared_memory as xshm

        name = self._kv_export_region_name(generation_id)
        byte_size = int(cache.size) * cache.dtype.itemsize
        self.drop_kv_region(generation_id)  # a reused id supersedes
        owner = xshm.create_shared_memory_region(name, byte_size)
        try:
            region = _XlaShmRegion(
                name, xshm.get_raw_handle(owner), 0, byte_size)
        except Exception:
            xshm.destroy_shared_memory_region(owner)
            raise
        region._owner_handle = owner
        region.put_device_array(0, cache)
        with self._shm_lock:
            self._xla_shm[name] = region
            self._kv_exports[generation_id] = (
                name, int(position), tuple(cache.shape), str(cache.dtype))
            self._kv_export_claims.discard(generation_id)

    def import_kv_region(self, generation_id):
        """``(device cache, parked position)`` of a prior export, or
        None when the generation never exported, the region was
        unregistered, or the device segment is no longer live (e.g. a
        cross-process attach) — the caller then falls back to the
        re-prefill path, gracefully."""
        with self._shm_lock:
            entry = self._kv_exports.get(generation_id)
            if entry is None:
                return None
            name, position, _, _ = entry
            region = self._xla_shm.get(name)
        if region is None:
            with self._shm_lock:
                self._kv_exports.pop(generation_id, None)
            return None
        cache = region.handle.get_jax_segment(0)
        if cache is None:
            return None
        return cache, position

    def drop_kv_region(self, generation_id):
        """Release a generation's KV export (resume consumed it, or its
        replay entry aged out): region unregistered, host window
        unlinked.  Idempotent."""
        with self._shm_lock:
            entry = self._kv_exports.pop(generation_id, None)
            self._kv_export_claims.discard(generation_id)
            region = self._xla_shm.pop(entry[0], None) if entry else None
        if region is not None:
            region.close()
            self._destroy_owned(region)

    def kv_export_descriptor(self, generation_id):
        """Wire descriptor of a live KV export — the transfer handle a
        decode-role replica attaches to re-scatter a prefill leg's
        pages instead of re-prefilling (docs/resilience.md
        "Disaggregated prefill/decode").

        The contract is **one-shot**: the first fetch claims the export
        (the disagg orchestrator hands it to exactly one decode
        replica), a second fetch for the same generation raises the
        typed 409 ``KvExportConflict``, and a fetch for a generation
        with no live export (never exported, dropped, or TTL-expired
        with its replay entry) raises the typed 404 ``KvExportNotFound``
        — the caller falls back to the fused re-prefill path instead of
        crashing later inside the ``paged_gather`` scatter.

        Fetching forces the device-resident pages into the region's
        host staging window (one device→host sync, outside the shm
        lock) so a cross-process attach reads real bytes.  Returns a
        JSON-able dict::

            {"generation_id", "name", "raw_handle", "position",
             "shape", "dtype", "byte_size", "device_ordinal"}
        """
        from tritonclient.utils import xla_shared_memory as xshm

        with self._shm_lock:
            entry = self._kv_exports.get(generation_id)
            region = self._xla_shm.get(entry[0]) if entry else None
            if entry is None or region is None:
                if entry is not None:
                    # region unregistered under the record: forget it
                    self._kv_exports.pop(generation_id, None)
                    self._kv_export_claims.discard(generation_id)
                raise KvExportNotFound(
                    "no live KV export for generation '{}' (never "
                    "exported, dropped, or expired); fall back to "
                    "prefill".format(generation_id))
            if generation_id in self._kv_export_claims:
                raise KvExportConflict(
                    "KV export for generation '{}' already claimed: the "
                    "transfer contract is one-shot".format(generation_id))
            self._kv_export_claims.add(generation_id)
            name, position, shape, dtype = entry
        try:
            # device->host sync + handle serialization outside the lock
            # (syscall/DMA work never holds _shm_lock)
            owner = getattr(region, "_owner_handle", None)
            handle = owner if owner is not None else region.handle
            region.read(0, region.byte_size)
            raw = xshm.get_raw_handle(handle)
        except Exception:
            with self._shm_lock:  # leave the export fetchable again
                self._kv_export_claims.discard(generation_id)
            raise
        return {
            "generation_id": generation_id,
            "name": name,
            "raw_handle": raw.decode("ascii"),
            "position": int(position),
            "shape": list(shape),
            "dtype": dtype,
            "byte_size": int(region.byte_size),
            "device_ordinal": int(region.device_ordinal),
        }

    def import_kv_descriptor(self, descriptor):
        """Attach a KV export published by another replica from its wire
        descriptor: ``(device cache, parked position)`` ready for the
        scheduler's attach-admission path.  In-process the device
        segment aliases zero-copy; cross-process the host staging
        window is read once and device_put.  A malformed or unreachable
        descriptor raises the typed 404 ``KvExportNotFound`` — at
        admission time, never a late crash inside the scatter."""
        import jax.numpy as jnp
        from tritonclient.utils import xla_shared_memory as xshm

        try:
            raw = descriptor["raw_handle"]
            shape = tuple(int(d) for d in descriptor["shape"])
            try:
                dtype = np.dtype(descriptor["dtype"])
            except TypeError:
                # extension dtypes (bfloat16 — the default KV wire
                # dtype) resolve only once ml_dtypes registers them
                import ml_dtypes  # noqa: F401

                dtype = np.dtype(descriptor["dtype"])
            position = int(descriptor["position"])
            byte_size = int(descriptor.get("byte_size")
                            or int(np.prod(shape)) * dtype.itemsize)
        except (KeyError, TypeError, ValueError) as e:
            raise KvExportNotFound(
                "malformed kv-export descriptor: {}".format(e))
        try:
            handle = xshm.attach_from_raw_handle(raw)
        except Exception as e:
            raise KvExportNotFound(
                "kv export unreachable (region gone?): {}".format(e))
        try:
            cache = handle.get_jax_segment(0)
            if cache is not None:  # in-process: zero-copy alias
                if tuple(cache.shape) != shape:
                    cache = cache.reshape(shape)
                return cache, position
            host = np.frombuffer(
                handle.read_bytes(0, byte_size), dtype=dtype).reshape(shape)
            return jnp.asarray(host), position
        except KvExportNotFound:
            raise
        except Exception as e:
            raise KvExportNotFound(
                "kv export attach failed for region '{}': {}".format(
                    descriptor.get("name", "?"), e))
        finally:
            handle.detach()

    def _drop_export_entry_locked(self, region_name):
        """Forget the export record pointing at ``region_name`` (the
        region itself is being unregistered by the caller).  Called
        with ``_shm_lock`` held."""
        for gid, entry in list(self._kv_exports.items()):
            if entry[0] == region_name:
                self._kv_exports.pop(gid, None)
                self._kv_export_claims.discard(gid)

    @staticmethod
    def _destroy_owned(region):
        """Unlink the owner handle of a server-created region (client
        regions are owned by the client; their unregister only
        detaches)."""
        owner = getattr(region, "_owner_handle", None)
        if owner is not None:
            from tritonclient.utils import xla_shared_memory as xshm

            try:
                xshm.destroy_shared_memory_region(owner)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass

    def _shm_region(self, name):
        region = self._system_shm.get(name) or self._xla_shm.get(name)
        if region is None:
            raise ServerError(
                "Unable to find shared memory region: '{}'".format(name)
            )
        return region

    def xla_shm_region(self, name):
        """Public lookup of a registered XLA region (for models that park
        device state in shm, e.g. llama KV caches); raises ServerError when
        unknown."""
        region = self._xla_shm.get(name)
        if region is None:
            raise ServerError(
                "Unable to find xla shared memory region: '{}'".format(name)
            )
        return region

    @staticmethod
    def _check_shm_bounds(region, byte_size, offset, direction):
        """Typed 400 for a shared-memory reference outside its
        registered region — at request time, instead of an opaque
        buffer/mmap error deep inside the shm read/write."""
        try:
            byte_size = int(byte_size)
            offset = int(offset)
        except (TypeError, ValueError):
            raise ServerError(
                "shared-memory {} reference for region '{}' must carry "
                "integer byte_size/offset (got byte_size={!r}, "
                "offset={!r})".format(
                    direction, region.name, byte_size, offset
                ),
                code=400,
            )
        if byte_size < 0 or offset < 0:
            raise ServerError(
                "shared-memory {} reference for region '{}' must be "
                "non-negative (got byte_size={}, offset={})".format(
                    direction, region.name, byte_size, offset
                ),
                code=400,
            )
        if offset + byte_size > region.byte_size:
            raise ServerError(
                "shared-memory {} reference out of bounds for region "
                "'{}': offset {} + byte_size {} exceeds the registered "
                "size {}".format(
                    direction, region.name, offset, byte_size,
                    region.byte_size,
                ),
                code=400,
            )
        return byte_size, offset

    def read_shm_input(self, region_name, byte_size, offset, datatype, shape):
        """Materialize an input tensor from a registered shm region.

        For XLA regions holding live device buffers this returns the
        ``jax.Array`` itself — no host copy."""
        # shm-read-failure chaos hook (scoped: multi-replica harnesses
        # can fail one replica's shm plane)
        faults.fire("core.shm_read", self.fault_scope)
        region = self._shm_region(region_name)
        byte_size, offset = self._check_shm_bounds(
            region, byte_size, offset, "input"
        )
        if isinstance(region, _XlaShmRegion):
            arr = region.get_device_array(offset, datatype, shape)
            if arr is not None:
                # the zero-copy fast path: count the logical tensor
                # size (the bytes that did NOT need to cross the host)
                self._m_shm_read.inc(
                    int(arr.size) * arr.dtype.itemsize)
                return arr
        self._m_shm_read.inc(byte_size)
        raw = region.read(offset, byte_size)
        if datatype == "BYTES":
            return deserialize_bytes_tensor(raw).reshape(
                [s for s in shape]
            )
        np_dtype = triton_to_np_dtype(datatype)
        return np.frombuffer(raw, dtype=np_dtype).reshape(shape)

    def write_shm_output(self, region_name, offset, array, datatype):
        """Write an output tensor into a registered shm region.

        jax.Array outputs written to an in-process XLA region stay on device."""
        region = self._shm_region(region_name)
        if isinstance(region, _XlaShmRegion) and not isinstance(
            array, np.ndarray
        ):
            # the device-resident path is bounds-checked too (.nbytes
            # is metadata on jax arrays — no transfer): a ring slot or
            # output reference past the registered size must be the
            # same typed 400 the host path raises, not a later silent
            # overrun when the segment syncs to the host window
            nbytes = int(array.size) * array.dtype.itemsize
            _, offset = self._check_shm_bounds(region, nbytes, offset,
                                               "output")
            if region.put_device_array(offset, array):
                self._m_shm_written.inc(nbytes)
                return
        if datatype == "BYTES":
            serialized = serialize_byte_tensor(np.asarray(array, dtype=object))
            data = serialized.item() if serialized.size > 0 else b""
        else:
            data = np.ascontiguousarray(np.asarray(array)).tobytes()
        _, offset = self._check_shm_bounds(region, len(data), offset,
                                           "output")
        region.write(offset, data)
        self._m_shm_written.inc(len(data))

    #: bytes per token-ring slot: one int32 TOKEN + one fp32 LOGPROB,
    #: little-endian, packed back to back — the whole per-step event
    #: payload once the tensors travel through shared memory
    SHM_RING_SLOT_BYTES = 8

    def write_shm_ring_slot(self, region_name, offset, token, logprob):
        """Write one generation step into its token-ring slot (the
        shm-delivery twin of the TOKEN/LOGPROB decoupled response):
        int32 token + fp32 logprob packed little-endian, ONE
        bounds-checked region write per step — the same
        :meth:`write_shm_output` plumbing (lookup, bounds, write,
        byte accounting) without paying it twice on the per-token hot
        path.  A ring descriptor pointing past the region is a typed
        400 on THAT step, never an overrun."""
        import struct

        data = struct.pack("<if", int(token), float(logprob))
        region = self._shm_region(region_name)
        _, offset = self._check_shm_bounds(region, len(data), offset,
                                           "output")
        region.write(offset, data)
        self._m_shm_written.inc(len(data))

    def write_shm_ring_seq_word(self, region_name, offset, word):
        """Stamp one 4-byte seqlock word for a ring slot (requests
        opting in via ``shm_ring_seq_base`` — see tpuserver.shm_ring).
        Same bounds-checked plumbing as the slot write: a seq-word
        array pointing past the region is a typed 400 on that step."""
        from tpuserver import shm_ring

        data = shm_ring.pack_word(word)
        region = self._shm_region(region_name)
        _, offset = self._check_shm_bounds(region, len(data), offset,
                                           "output")
        region.write(offset, data)
        self._m_shm_written.inc(len(data))

    # -- inference ---------------------------------------------------------

    @staticmethod
    def _resolve_deadline(request):
        """One canonical monotonic deadline per request: the ``timeout``
        request parameter (microseconds, Triton semantics) combined with
        any transport deadline the frontend stamped on
        ``request.deadline`` (the gRPC context deadline) — the sooner
        wins.  Stored back on the request so downstream consumers (the
        decode scheduler) see the same bound."""
        deadline = getattr(request, "deadline", None)
        t = request.parameters.get("timeout")
        if t:
            try:
                param_deadline = time.monotonic() + int(t) / 1e6
            except (TypeError, ValueError):
                raise ServerError(
                    "request parameter 'timeout' must be an integer "
                    "microsecond count (got {!r})".format(t)
                )
            deadline = (
                param_deadline
                if deadline is None
                else min(deadline, param_deadline)
            )
        request.deadline = deadline
        return deadline

    @staticmethod
    def _check_deadline(deadline):
        if deadline is not None and time.monotonic() >= deadline:
            raise DeadlineExceeded(
                "request deadline expired before execution"
            )

    def infer(self, request):
        """Execute one inference request; returns InferResponse.

        Decoupled models are rejected here (use ``infer_stream``), matching
        server behavior for non-streaming endpoints.
        """
        t0 = time.monotonic()
        self._m_infer_count.inc()
        try:
            deadline = self._resolve_deadline(request)
            self._check_deadline(deadline)
            self._enter_inflight()
            try:
                model = self._get_model(
                    request.model_name, request.model_version
                )
                if model.decoupled:
                    raise ServerError(
                        "model '{}' is a decoupled model: it can only be "
                        "served over the streaming endpoint".format(
                            model.name)
                    )
                return self._execute(model, request)
            finally:
                self._exit_inflight()
        except ServerError as e:
            # typed failures count by wire code: 429 = shed, 504 =
            # deadline, 503 = draining/shutdown — the shed/deadline/
            # error breakdown /metrics carries per verb
            self._count_error("infer", getattr(e, "code", 500))
            raise
        finally:
            self._m_infer_hist.observe(time.monotonic() - t0)

    def infer_stream(self, request):
        """Execute a (possibly decoupled) request; yields InferResponse(s).

        With the ``triton_enable_empty_final_response`` request parameter a
        trailing empty response marked ``triton_final_response`` is emitted
        so clients can detect completion of data-dependent-length streams.
        """
        t0 = time.monotonic()
        self._m_stream_count.inc()
        try:
            deadline = self._resolve_deadline(request)
            self._check_deadline(deadline)
            self._enter_inflight()
            try:
                yield from self._infer_stream_inner(request)
            finally:
                self._exit_inflight()
        except ServerError as e:
            self._count_error("stream_infer", getattr(e, "code", 500))
            raise
        finally:
            # streamed verbs measure submit-to-terminal-event: the
            # duration covers the whole generation, not just dispatch
            self._m_stream_hist.observe(time.monotonic() - t0)

    def _infer_stream_inner(self, request):
        want_final = bool(
            request.parameters.get("triton_enable_empty_final_response")
        )
        model = self._get_model(request.model_name, request.model_version)
        if not model.decoupled:
            resp = self._execute(model, request)
            if want_final:
                resp.parameters["triton_final_response"] = True
            yield resp
            return
        t0 = time.monotonic_ns()
        inputs = dict(request.inputs)
        t1 = time.monotonic_ns()
        count = 0
        try:
            for out in model.execute_stream(inputs, request):
                # per-response deadline enforcement covers EVERY
                # decoupled model (the scheduler path also self-expires;
                # the single-stream path relies on this check): a token
                # produced past the deadline belongs to a request whose
                # client has stopped waiting
                self._check_deadline(request.deadline)
                count += 1
                extra_params = None
                if RESPONSE_PARAMS_KEY in out:
                    out = dict(out)
                    extra_params = out.pop(RESPONSE_PARAMS_KEY)
                resp = self._make_response(model, request, out,
                                           mark_final=False)
                if extra_params:
                    resp.parameters.update(extra_params)
                if want_final:
                    resp.parameters["triton_final_response"] = False
                yield resp
        except Exception as e:
            self._stats[model.name].record(0, 0, 0, 0, 0, ok=False)
            if isinstance(e, ServerError):
                # the scheduler raises the canonical tpuserver.errors
                # types directly (deadline 504, quarantined slot 422,
                # unknown resume id 404 — one definition, R4-enforced).
                # Class/code/retry_after pass through untouched, but a
                # multi-model server needs attribution: scheduler
                # messages carry no model name, so logs/clients could
                # not tell whose stream failed
                prefix = "model '{}': ".format(model.name)
                if (e.args and isinstance(e.args[0], str)
                        and not e.args[0].startswith(prefix)):
                    e.args = (prefix + e.args[0],) + e.args[1:]
                raise
            # the two scheduler-lifecycle signals that stay scheduler-
            # local types map to their typed wire forms here:
            # admission-full -> 429 (+Retry-After), closed/draining ->
            # 503 — instead of the generic 500 wrap
            if isinstance(e, _scheduler.AdmissionQueueFull):
                # the adaptive shed controller computes Retry-After
                # from its current control interval — the pace the
                # queue is actually draining; the fixed-cliff shed
                # keeps the 1s default
                raise Overloaded(
                    "model '{}': {}".format(model.name, e),
                    retry_after=getattr(e, "retry_after", None) or 1)
            if isinstance(e, _scheduler.SchedulerClosed):
                raise ShuttingDown("model '{}': {}".format(model.name, e))
            raise ServerError(
                "inference failed for model '{}': {}".format(model.name, e),
                code=500,
            )
        t2 = time.monotonic_ns()
        self._stats[model.name].record(
            self._batch_of(model, inputs), 0, t1 - t0, t2 - t1, 0
        )
        if want_final:
            yield InferResponse(
                model.name, model.version, request.id, [],
                parameters={"triton_final_response": True},
            )

    def _batch_of(self, model, inputs):
        if model.max_batch_size > 0 and inputs:
            first = next(iter(inputs.values()))
            # .shape/.ndim are metadata on numpy and jax arrays alike;
            # np.asarray here would force a device→host transfer when the
            # input is a device-resident jax.Array from an XLA shm region.
            shape = getattr(first, "shape", None)
            if shape is None:
                shape = np.asarray(first).shape
            return int(shape[0]) if len(shape) > 0 else 1
        return 1

    def _execute(self, model, request):
        stats = self._stats[model.name]
        t_queue0 = time.monotonic_ns()
        # compute_input: materialize shm-resident inputs already done by
        # frontend; here validate presence.
        t_ci0 = time.monotonic_ns()
        inputs = dict(request.inputs)
        declared = {t.name: t for t in model.inputs}
        for t in model.inputs:
            if t.name not in inputs:
                raise ServerError(
                    "expected {} inputs but got {} inputs for model '{}': "
                    "missing '{}'".format(
                        len(model.inputs), len(inputs), model.name, t.name
                    )
                )
        for name in inputs:
            if declared and name not in declared:
                raise ServerError(
                    "unexpected inference input '{}' for model '{}'".format(
                        name, model.name
                    )
                )
        t_cf0 = time.monotonic_ns()
        batch_queue_ns = 0
        try:
            if model.ensemble_steps is not None:
                outputs = self._execute_ensemble(model, inputs, request)
            elif model.sequence:
                outputs = self._execute_sequence(model, inputs, request)
            elif self._batchable(model, inputs, request):
                # the batcher reports how long this request sat in its
                # batching window: that wait lands in the KServe `queue`
                # bucket, so the profiler's server-side breakdown can
                # tell queueing from actual device compute
                outputs, batch_queue_ns = self._batcher_of(model).submit(
                    inputs, int(next(iter(inputs.values())).shape[0])
                )
            else:
                outputs = model.execute(inputs, request)
        except ServerError:
            stats.record(0, 0, 0, 0, 0, ok=False)
            raise
        except Exception as e:
            stats.record(0, 0, 0, 0, 0, ok=False)
            # malformed tensors surface as ValueError from the model's
            # numpy/jax ops: a client error (400), matching the batched
            # path and the frontends' own ValueError mapping
            raise ServerError(
                "inference failed for model '{}': {}".format(model.name, e),
                code=400 if isinstance(e, ValueError) else 500,
            )
        t_co0 = time.monotonic_ns()
        # the deadline is a contract, not advice: a result produced past
        # it is reported as 504 (the client has stopped waiting) and
        # counted as a failure in the model stats
        if request.deadline is not None and time.monotonic() >= (
            request.deadline
        ):
            stats.record(0, 0, 0, 0, 0, ok=False)
            raise DeadlineExceeded(
                "request deadline expired during execution"
            )
        resp = self._make_response(model, request, outputs)
        t_end = time.monotonic_ns()
        stats.record(
            self._batch_of(model, inputs),
            (t_ci0 - t_queue0) + batch_queue_ns,
            t_cf0 - t_ci0,
            max(0, (t_co0 - t_cf0) - batch_queue_ns),
            t_end - t_co0,
        )
        return resp

    def _batchable(self, model, inputs, request):
        """Route through the dynamic batcher? Requires the model to opt
        in, host (numpy) inputs with a leading batch dim, one consistent
        row count, and no per-request parameters (batched execution sees
        no request object)."""
        if not (model.dynamic_batching and model.max_batch_size > 1):
            return False
        # lifecycle-only parameters (deadline/priority plumbing) don't
        # make a request un-batchable — the deadline is enforced in
        # infer(), not inside batched execution
        extra_params = set(request.parameters) - {"timeout", "priority"}
        if extra_params or not inputs:
            return False
        on_device = getattr(model, "device_kind", "") == "tpu"
        rows = None
        for arr in inputs.values():
            ok = isinstance(arr, np.ndarray)
            if not ok and on_device:
                # device-resident inputs (XLA-shm fast path) batch too —
                # the batcher stacks them on device, no host copy
                import jax

                ok = isinstance(arr, jax.Array)
            if not ok or getattr(arr, "ndim", 0) < 1:
                return False
            if rows is None:
                rows = arr.shape[0]
            elif arr.shape[0] != rows:
                return False
        return True

    def _batcher_of(self, model):
        batcher = self._batchers.get(model.name)
        if batcher is None:
            with self._lock:
                if self._closed:
                    # a request racing close() must not lazily resurrect
                    # a batcher whose stop() already ran
                    raise ServerError("server is shutting down", code=503)
                batcher = self._batchers.get(model.name)
                if batcher is None:
                    batcher = _DynamicBatcher(model)
                    self._batchers[model.name] = batcher
        return batcher

    def attach_frontend(self):
        """Frontends register on start(); the last detach closes the
        core's background workers, so frontend shutdown paths reach
        batcher stop()/unload errors instead of leaking threads."""
        with self._lock:
            self._frontends += 1
            self._closed = False  # re-attach after close re-opens
        with self._inflight_cond:
            if self._state == "stopped":
                self._state = "ready"

    def detach_frontend(self):
        to_stop = []
        with self._lock:
            self._frontends = max(0, self._frontends - 1)
            if self._frontends == 0:
                # decide AND mark closed under the same lock hold: a
                # concurrent attach_frontend can only run before (it
                # bumps the count, no close) or after (it re-opens and
                # batchers lazily recreate) — never see a close land
                # under a live attach
                self._closed = True
                to_stop, self._batchers = list(
                    self._batchers.values()), {}
        for b in to_stop:
            b.stop()

    def close(self):
        """Stop background workers (dynamic batchers, and any model-owned
        schedulers via the model's own ``close``).  Safe to call twice;
        after close, batched/scheduled inference is rejected rather than
        lazily recreating workers."""
        with self._inflight_cond:
            self._state = "stopped"
            self._inflight_cond.notify_all()
        with self._lock:
            self._closed = True
            batchers, self._batchers = list(self._batchers.values()), {}
        for b in batchers:
            b.stop()
        for model in list(self._models.values()):
            closer = getattr(model, "close", None)
            if callable(closer):
                closer()
        # server-owned KV exports die with the server: their host
        # windows unlink so healed replicas never inherit stale
        # /dev/shm files (the chaos --shm zero-leak invariant)
        with self._shm_lock:
            export_ids = list(self._kv_exports)
        for gid in export_ids:
            self.drop_kv_region(gid)

    def _execute_sequence(self, model, inputs, request):
        if request.sequence_id == 0:
            raise ServerError(
                "inference request to model '{}' must specify a non-zero "
                "sequence id".format(model.name)
            )
        self._expire_idle_sequences(model)
        key = (model.name, request.sequence_id)
        if request.sequence_start:
            state = None
        else:
            if key not in self._sequence_state:
                raise ServerError(
                    "inference request for sequence {} to model '{}' must "
                    "specify the START flag on the first request of the "
                    "sequence".format(request.sequence_id, model.name)
                )
            state = self._sequence_state[key][0]
        outputs, new_state = model.execute_sequence(inputs, state, request)
        if request.sequence_end:
            self._sequence_state.pop(key, None)
        else:
            self._sequence_state[key] = (new_state, time.monotonic())
        return outputs

    def _expire_idle_sequences(self, model):
        """Drop sequences idle beyond their model's
        ``max_sequence_idle_us`` so abandoned sequences (no END request)
        cannot grow state unboundedly — role of the reference sequence
        batcher's max_sequence_idle_microseconds expiry.  One sweep
        covers EVERY model's sequences (each judged by its own idle
        window), so a model that stops receiving traffic still gets its
        abandoned state reclaimed by any other model's requests.  Swept
        at most once per triggering model's half-window (min 50 ms) so
        the scan stays off the per-request hot path, over an atomic
        snapshot so concurrent frontend threads can insert/pop freely."""
        idle_us = getattr(model, "max_sequence_idle_us", 60_000_000)
        now = time.monotonic()
        sweep_gap = max(idle_us / 1e6 / 2.0, 0.05)
        if now - self._last_sequence_sweep < sweep_gap:
            return
        self._last_sequence_sweep = now
        idle_cache = {}
        expired = []
        for key, (_, touched) in list(self._sequence_state.items()):
            name = key[0]
            if name not in idle_cache:
                owner = self._models.get(name)
                idle_cache[name] = getattr(
                    owner, "max_sequence_idle_us", 60_000_000
                ) if owner is not None else 0
            if touched < now - idle_cache[name] / 1e6:
                expired.append(key)
        for key in expired:
            self._sequence_state.pop(key, None)

    def _execute_ensemble(self, model, inputs, request):
        tensors = dict(inputs)
        for step in model.ensemble_steps:
            sub = self._get_model(step["model_name"])
            sub_inputs = {
                model_in: tensors[ens_name]
                for model_in, ens_name in step["input_map"].items()
            }
            sub_req = InferRequest(
                sub.name, "", request.id, sub_inputs, None, request.parameters
            )
            sub_out = sub.execute(sub_inputs, sub_req)
            for model_out, ens_name in step["output_map"].items():
                tensors[ens_name] = sub_out[model_out]
        return {
            t.name: tensors[t.name] for t in model.outputs
        }

    def _classify(self, array, class_count, labels):
        """Top-k classification strings 'value:index[:label]' per batch row."""
        arr = np.asarray(array)
        squeeze = arr.ndim == 1
        mat = arr.reshape(1, -1) if squeeze else arr.reshape(arr.shape[0], -1)
        k = min(class_count, mat.shape[-1])
        idx = np.argsort(-mat, axis=-1)[:, :k]
        rows = []
        for r in range(mat.shape[0]):
            row = []
            for i in idx[r]:
                entry = "{:f}:{}".format(float(mat[r, i]), int(i))
                if labels is not None and int(i) < len(labels):
                    entry += ":" + labels[int(i)]
                row.append(entry.encode("utf-8"))
            rows.append(row)
        out = np.array(rows, dtype=np.object_)
        if squeeze:
            out = out.reshape(-1)
        return out

    #: delivery options of a default (no requested_outputs) response:
    #: one shared immutable dict instead of a per-output allocation on
    #: the hot path — consumers only read it
    _DEFAULT_DELIVERY = {"binary_data": True, "shm_region": None,
                         "shm_byte_size": 0, "shm_offset": 0}

    def _make_response(self, model, request, outputs, mark_final=True):
        declared = {t.name: t for t in model.outputs}
        requested = request.requested_outputs
        if not requested:
            # the overwhelmingly common shape (every output, wire
            # delivery, no classification): skip the RequestedOutput
            # and per-output delivery-dict allocations entirely —
            # measured at several percent of the simple-model
            # per-request hot path (ISSUE 11 headline recapture)
            resp_outputs = []
            for name, array in outputs.items():
                spec = declared.get(name)
                datatype = spec.datatype if spec is not None else None
                if not datatype:
                    datatype = _np_to_wire(array)
                np_arr = np.asarray(array) if not hasattr(
                    array, "addressable_shards"
                ) else array
                resp_outputs.append((
                    {"name": name, "datatype": datatype,
                     "shape": list(np_arr.shape)},
                    np.asarray(np_arr),
                    self._DEFAULT_DELIVERY,
                ))
            return InferResponse(
                model.name, model.version, request.id, resp_outputs
            )
        wanted = []
        for ro in requested:
            if ro.name not in outputs:
                raise ServerError(
                    "unexpected inference output '{}' for model "
                    "'{}'".format(ro.name, model.name)
                )
            wanted.append(ro)

        resp_outputs = []
        for ro in wanted:
            array = outputs[ro.name]
            spec = declared.get(ro.name)
            if ro.class_count > 0:
                labels = (model.labels or {}).get(ro.name)
                array = self._classify(array, ro.class_count, labels)
                datatype = "BYTES"
            else:
                datatype = spec.datatype if spec is not None else None
                if datatype is None or datatype == "":
                    datatype = _np_to_wire(array)
            np_arr = np.asarray(array) if not hasattr(
                array, "addressable_shards"
            ) else array
            shape = list(np_arr.shape)
            delivery = {
                "binary_data": ro.binary_data,
                "shm_region": ro.shm_region,
                "shm_byte_size": ro.shm_byte_size,
                "shm_offset": ro.shm_offset,
            }
            if ro.shm_region is not None:
                # .nbytes is metadata on both numpy and jax arrays; avoid
                # np.asarray here — it would force a device→host transfer
                # for outputs that stay device-resident in an XLA region.
                expected = (
                    serialized_byte_size(np.asarray(np_arr, dtype=object))
                    if datatype == "BYTES"
                    else int(np_arr.nbytes)
                )
                if expected > ro.shm_byte_size:
                    raise ServerError(
                        "shared memory size specified with the request for "
                        "output '{}' ({} bytes) should be at least {} "
                        "bytes".format(ro.name, ro.shm_byte_size, expected)
                    )
                self.write_shm_output(
                    ro.shm_region, ro.shm_offset, np_arr, datatype
                )
                resp_outputs.append(
                    (
                        {"name": ro.name, "datatype": datatype,
                         "shape": shape},
                        None,
                        delivery,
                    )
                )
            else:
                resp_outputs.append(
                    (
                        {"name": ro.name, "datatype": datatype,
                         "shape": shape},
                        np.asarray(np_arr),
                        delivery,
                    )
                )
        return InferResponse(
            model.name, model.version, request.id, resp_outputs
        )


def install_sigterm_drain(server, drain_timeout=30.0):
    """Install a SIGTERM handler that gracefully drains ``server``:
    admission stops and readiness flips immediately (so load balancers
    route away), in-flight generations finish within ``drain_timeout``
    seconds, and the rest fail deterministically.  The drain runs on a
    worker thread — signal handlers must return promptly.  Returns the
    previous handler (pass it back to ``signal.signal`` to restore).
    Main-thread only, as all Python signal installation is."""
    import signal

    def _handler(signum, frame):
        threading.Thread(
            target=server.drain,
            args=(drain_timeout,),
            name="sigterm-drain",
            daemon=True,
        ).start()

    return signal.signal(signal.SIGTERM, _handler)


def _np_to_wire(array):
    from tritonclient.utils import np_to_triton_dtype

    dt = np_to_triton_dtype(np.asarray(array).dtype)
    return dt or "FP32"
