"""Clock discipline: the one sanctioned wall-clock read.

Everything time-shaped in this codebase — deadlines, timeouts, liveness
stamps, backoff windows, TTLs — is ``time.monotonic()`` math, enforced
statically by tpulint rule R3 (wall clocks jump under NTP steps and
suspend/resume; a jumped deadline fires years early or never).  The
single exception is *wire-format reporting*: the KServe statistics
protocol's ``last_inference`` field is epoch milliseconds by contract.
That read lives here, behind one suppressed call, so every other
``time.time()`` in the tree is a finding, not a judgment call.
"""

import time


def wall_clock_ms():
    """Epoch milliseconds for wire-format reporting fields ONLY —
    never for deadline/liveness math (tpulint R3 bans wall-clock reads
    everywhere else)."""
    return int(time.time() * 1000)  # tpulint: disable=R3
