"""Disaggregated prefill/decode orchestration — the phase-split layer
over KV-export regions (ROADMAP item 2, docs/resilience.md
"Disaggregated prefill/decode").

Chunked prefill only stops prefill stalling decode *within* a replica;
under mixed long-prompt traffic TTFT still competes with ITL for the
same decode loop.  This module splits the two phases across the fleet:

- **prefill replicas** (``InferenceServer(role="prefill")``) run the
  admission's prefill (plus exactly one decode step) and publish the
  finished KV pages as a server-owned ``kvexport/<gen_id>`` region;
- **decode replicas** (``role="decode"``) attach the export, re-scatter
  it into their own page table, and stream from the second token — no
  re-prefill, token-identity preserved (greedy decode is deterministic
  and the attach path is A/B-pinned against the fused run in
  tests/test_disagg.py).

The orchestrator lives in the fleet router's admission path.  A fresh
generation admission becomes, when both role pools are routable:

1. a **prefill leg** — the original request with ``MAX_TOKENS=1`` and
   ``kv_phase=prefill``, routed with prefix affinity over the prefill
   pool (that is where the radix cache lives); its single token relays
   to the client immediately (it IS the TTFT) and its KV exports on
   finish;
2. a **KV transfer** — one ``GET /v2/kvexport/<gen_id>`` on the prefill
   replica: the one-shot claimed wire descriptor (typed 404 when the
   export is gone, 409 on a double claim — both fall back);
3. a **decode leg** — the router's existing handoff body (prompt +
   token 0, ``MAX_TOKENS`` shrunk by one) with the descriptor injected
   as ``kv_attach``, admitted on the least-loaded decode replica.

Every edge degrades to the fused path, token-identically: a fleet with
no role-tagged replicas (or a single replica) never enters this module;
a prefill leg that dies before its token is a plain failover; one that
dies after it — or a failed/conflicted descriptor fetch — becomes an
ordinary re-prefill handoff on the existing machinery.  Mid-handoff
death of either role therefore heals exactly like any other replica
death, which is what ``tools/chaos_smoke.py --disagg`` kills processes
to prove.

This module deliberately does not import ``tpuserver.router`` (the
router imports it); everything it needs from the router — replica
snapshots, pick_* routing, counters — is reached through the instance
handed to :class:`PhaseSplitOrchestrator`.
"""

import http.client
import json
import socket
import threading
import time
from urllib.parse import quote

#: The two dedicated phase roles a replica can advertise in its health
#: snapshot; anything else (None included) reads as "fused".
PREFILL_ROLE = "prefill"
DECODE_ROLE = "decode"
FUSED_ROLE = "fused"


def _coerce_int(value, default=0):
    try:
        return int(value)
    except (TypeError, ValueError):
        return default


#: Replica-side generation-id suffix for the prefill leg.  The leg's
#: record on the prefill replica is a COMPLETED one-token generation
#: (its ``MAX_TOKENS`` was rewritten to 1) — if it lived under the real
#: generation id, a router that crashed mid-split and recovered
#: ``home = prefill replica`` from its journal would resume against
#: that record, get an instant ``final``, and silently truncate the
#: stream to one token (chaos campaign seed 7: router_sigkill composed
#: with replica churn).  Under a derived id, that stale resume answers
#: typed-404 instead, which the relay loop already heals via a
#: token-identical re-prefill handoff.  Never digits after the tilde,
#: so the router's ``gen~offset`` handoff-epoch parsing cannot
#: mistake it.
PREFILL_LEG_ID_SUFFIX = "~prefill"


def prefill_leg_id(gen_id):
    """The replica-side generation id of ``gen_id``'s prefill leg."""
    return gen_id + PREFILL_LEG_ID_SUFFIX


def prefill_leg_body(body):
    """Rewrite a fresh admission body into its prefill leg: exactly one
    decode step (``MAX_TOKENS=1`` — the first token is the TTFT the
    split exists to protect), ``kv_phase=prefill`` so the replica
    exports the KV when the leg finishes, and the leg's DERIVED
    generation id (:func:`prefill_leg_id`) so the completed one-token
    record can never satisfy a resume of the real generation."""
    request = json.loads(body)
    inputs = []
    for tin in request.get("inputs") or []:
        tin = dict(tin)
        if tin.get("name") == "MAX_TOKENS":
            tin["data"] = [1]
        inputs.append(tin)
    request["inputs"] = inputs
    params = dict(request.get("parameters") or {})
    params["kv_phase"] = PREFILL_ROLE
    gid = str(params.get("generation_id") or "")
    if gid:
        params["generation_id"] = prefill_leg_id(gid)
    request["parameters"] = params
    return json.dumps(request).encode("utf-8")


def attach_body(handoff_body, descriptor):
    """Inject a KV-export wire descriptor into a handoff re-admission
    body: the decode replica imports it and scatters instead of
    re-prefilling.  The body stays a valid fused re-admission — a
    replica that cannot attach (export died under the claim) silently
    prefills the same prompt, token-identically."""
    request = json.loads(handoff_body)
    params = dict(request.get("parameters") or {})
    params["kv_attach"] = descriptor
    request["parameters"] = params
    return json.dumps(request).encode("utf-8")


class PhaseSplitOrchestrator:
    """Router-resident phase-split admission: role pools, the prefill
    leg, the KV transfer, and the disagg counters /metrics exposes."""

    def __init__(self, router):
        self._router = router
        self._lock = threading.Lock()
        self._splits = 0            # guarded-by: _lock
        self._fallbacks = {}        # reason -> count  # guarded-by: _lock
        self._transfers = 0         # guarded-by: _lock
        self._transfer_bytes = 0    # guarded-by: _lock
        self._transfer_ms = 0.0     # guarded-by: _lock
        self._prefill_queue_ms = 0.0  # guarded-by: _lock

    # -- pools & telemetry -------------------------------------------------

    def pools(self):
        """``(prefill, decode)`` replica lists by advertised role.
        Role-less replicas belong to neither: they serve the fused
        path (and any fallback pick), so a mixed fleet keeps its
        fused capacity out of the split's way."""
        prefill, decode = [], []
        for rep in self._router._replicas_snapshot():
            role = rep.role()
            if role == PREFILL_ROLE:
                prefill.append(rep)
            elif role == DECODE_ROLE:
                decode.append(rep)
        return prefill, decode

    def phase_queue_depth(self):
        """``{phase: queued + live generations}`` summed from the
        prober's health snapshots — the per-phase queue-depth signal
        (a deep prefill queue with idle decode capacity means the
        role targets are mis-sized, and vice versa)."""
        depths = {}
        for rep in self._router._replicas_snapshot():
            snap = rep.health()
            if not isinstance(snap, dict):
                continue
            role = snap.get("role") or FUSED_ROLE
            depth = _coerce_int(snap.get("inflight"))
            for stats in (snap.get("models") or {}).values():
                if isinstance(stats, dict):
                    depth += _coerce_int(stats.get("pending"))
                    depth += _coerce_int(stats.get("live_streams"))
            depths[role] = depths.get(role, 0) + depth
        return depths

    def _count_fallback(self, reason):
        with self._lock:
            self._fallbacks[reason] = self._fallbacks.get(reason, 0) + 1

    def stats(self):
        prefill, decode = self.pools()
        with self._lock:
            return {
                "splits": self._splits,
                "fallbacks": dict(self._fallbacks),
                "transfers": self._transfers,
                "transfer_bytes": self._transfer_bytes,
                "transfer_ms_total": self._transfer_ms,
                "prefill_queue_ms_total": self._prefill_queue_ms,
                "prefill_replicas": len(prefill),
                "decode_replicas": len(decode),
                "phase_queue_depth": self.phase_queue_depth(),
            }

    # -- admission ---------------------------------------------------------

    def try_admit(self, handler, gen):
        """Attempt the phase-split admission of a fresh generation.

        Returns None when the split does not apply (no role pools, no
        generate contract, too few tokens, explicit phase parameters):
        nothing was sent anywhere and the caller runs today's fused
        path, byte-identically.  Otherwise runs the prefill leg —
        relaying its token to the client through ``handler`` — and
        returns a plan dict:

        - ``{"terminal": "complete"|"error"|"fail"}`` — the generation
          already ended during the prefill leg (single-token request /
          EOS on token 0 / typed in-band failure);
        - ``{"rep", "body", "headers", "release"}`` — the prepared
          decode leg (``rep`` may be None when no replica is left;
          ``release`` is an optional callable freeing the export once
          the decode replica's first token proves the attach landed).
        """
        router = self._router
        if gen.prompt is None or not gen.prompt:
            return None
        if gen.max_tokens is None or gen.max_tokens < 2:
            return None  # nothing left for a decode leg to stream
        params = gen.request.get("parameters") or {}
        if params.get("kv_phase") or params.get("kv_attach"):
            return None  # explicit phase control: the caller drives
        prefill_pool, decode_pool = self.pools()
        if not prefill_pool or not decode_pool:
            return None  # fused fleet (or a single role): today's path
        rep = router.pick_for_generation(gen, replicas=prefill_pool)
        if rep is None:
            self._count_fallback("no_prefill_replica")
            return None
        gen.set_home(rep.url)
        body, headers = gen.upstream_request(resuming=False)
        outcome = self._run_prefill_leg(
            handler, gen, rep, prefill_leg_body(body), headers)
        if outcome == "error":
            return {"terminal": "error"}
        if outcome in ("rejected", "died") and gen.emitted() == 0:
            # nothing relayed anywhere: a plain failover back to the
            # fused admission path (which may pick any replica)
            self._count_fallback("prefill_" + outcome)
            return None
        descriptor = None
        if outcome == "final":
            # the export is published under the LEG's derived id (that
            # is the generation_id the prefill replica saw)
            descriptor = self._fetch_descriptor(
                rep, prefill_leg_id(gen.gen_id))
        else:
            # token 0 reached the client, then the leg died: the
            # export never finished — re-prefill handoff below
            self._count_fallback("prefill_died_after_token")
        handoff = gen.handoff_request()
        if handoff == b"":
            # EOS on token 0 (or a single-token budget racing the
            # check): the stream is complete
            return {"terminal": "complete"}
        if handoff is None:
            # an event without a TOKEN output made the generation
            # unresumable — cannot happen on the scheduler contract
            return {"terminal": "fail"}
        release = None
        if descriptor is not None:
            handoff = attach_body(handoff, descriptor)
            release = self._releaser(rep, prefill_leg_id(gen.gen_id))
            with self._lock:
                self._splits += 1
        decode_rep = (router.pick_replica(replicas=decode_pool)
                      or router.pick_for_generation(
                          gen, exclude={rep.url}))
        if decode_rep is None:
            # no decode replica AND no fallback: let the caller's
            # retry loop fail typed exactly like the fused path
            decode_rep = router.pick_for_generation(gen)
        if decode_rep is not None:
            gen.set_home(decode_rep.url, rebase=True)
        return {
            "rep": decode_rep,
            "body": handoff,
            "headers": {"Content-Type": "application/json"},
            "release": release,
        }

    # -- legs --------------------------------------------------------------

    def _run_prefill_leg(self, handler, gen, rep, body, headers):
        """POST the prefill leg and relay its events (normally exactly
        one token) to the client through the handler's recording relay.
        Returns ``"final"`` / ``"error"`` / ``"died"`` / ``"rejected"``.
        """
        router = self._router
        t0 = time.monotonic()
        conn = None
        rep.begin_request()
        try:
            conn = http.client.HTTPConnection(
                rep.host, rep.port, timeout=router._read_timeout_s)
            conn.request("POST", gen.path, body=body, headers=headers)
            resp = conn.getresponse()
            if resp.status != 200:
                resp.read()
                rep.note_typed_failure()
                return "rejected"

            def note_first():
                elapsed = time.monotonic() - t0
                with self._lock:
                    self._prefill_queue_ms += elapsed * 1000.0
                # the prefill leg's TTFT feeds the replica's stream
                # digest the same way a fused admission's does
                rep.note_latency("generate_stream", elapsed)

            outcome = handler._relay_events(gen, resp, note_first)
            if outcome == "died":
                rep.mark_unreachable()
            return outcome
        except (ConnectionError, socket.timeout, OSError,
                http.client.HTTPException):
            rep.mark_unreachable()
            return "died"
        finally:
            rep.end_request()
            if conn is not None:
                conn.close()

    def _fetch_descriptor(self, rep, gen_id):
        """One-shot KV-export descriptor fetch, or None (counted, by
        reason) — a missing/claimed/unreachable export means the decode
        leg re-prefills instead, it never means a user-visible error."""
        router = self._router
        t0 = time.monotonic()
        conn = None
        try:
            conn = http.client.HTTPConnection(
                rep.host, rep.port, timeout=router._probe_timeout_s)
            conn.request("GET", "/v2/kvexport/" + quote(gen_id, safe=""))
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                self._count_fallback(
                    "descriptor_conflict" if resp.status == 409
                    else "descriptor_missing")
                return None
            descriptor = json.loads(data)
            elapsed_ms = (time.monotonic() - t0) * 1000.0
            with self._lock:
                self._transfers += 1
                self._transfer_ms += elapsed_ms
                self._transfer_bytes += _coerce_int(
                    descriptor.get("byte_size"))
            return descriptor
        except (ConnectionError, socket.timeout, OSError,
                http.client.HTTPException, ValueError):
            self._count_fallback("descriptor_unreachable")
            return None
        finally:
            if conn is not None:
                conn.close()

    def _releaser(self, rep, gen_id):
        """Deferred, best-effort export release: fired (off the relay
        hot path) once the decode leg's first token proves the attach
        consumed the region.  A leg that dies before that leaves the
        claim to the prefill replica's replay-TTL sweep — late cleanup,
        never a dangling attach."""
        def release():
            def _post():
                conn = None
                try:
                    conn = http.client.HTTPConnection(
                        rep.host, rep.port,
                        timeout=self._router._probe_timeout_s)
                    conn.request(
                        "POST",
                        "/v2/kvexport/{}/release".format(
                            quote(gen_id, safe="")))
                    conn.getresponse().read()
                except (ConnectionError, socket.timeout, OSError,
                        http.client.HTTPException):
                    pass  # TTL sweep owns the backstop
                finally:
                    if conn is not None:
                        conn.close()
            threading.Thread(
                target=_post, name="kvexport-release", daemon=True
            ).start()
        return release
